"""Fleet self-healing: supervised replica lifecycle (ISSUE 18).

PR 17's ``spawn_replicas`` was fire-and-forget: a replica that died
stayed dead (and un-reaped) until an operator noticed the router's
eligible set shrink. The reference ran every daemon under
``pio-start-all`` with pidfile lifecycle management; production serving
assumes a self-healing control loop above the router's fault isolation.
``FleetSupervisor`` is that loop — it owns the replica subprocesses
end-to-end:

- **Reaping** — a poll pass ``Popen.poll()``s every child, so an exited
  replica is reaped immediately (no zombies) and its exit code is
  logged with its port. A clean exit (rc 0) is operator intent
  (``pio fleet drain --stop``, a direct ``/stop``) — the replica goes
  to ``stopped``, never respawned and never counted toward the crash
  window.
- **Respawn with jittered exponential backoff** — a crashed replica is
  respawned on its ORIGINAL port (the router's rendezvous hash and the
  fleet state file both key on it), after ``backoff_base_s * 2^(n-1)``
  capped at ``backoff_cap_s``, with ±20% jitter so a correlated crash
  across replicas does not produce a thundering-herd respawn. The
  exponent is the death count inside the sliding crash window, so a
  crash loop that briefly reaches ready between deaths still escalates;
  the window forgetting old deaths is what resets it.
- **Crash-loop quarantine** — ``max_respawns`` deaths inside the
  sliding ``crash_window_s`` window mean respawning is not helping
  (bad model blob, poisoned port, OOM loop): the replica is
  **quarantined** — reported to the router (``set_quarantined``) so
  rendezvous traffic redistributes to its siblings, dropped from the
  fleet state file's active set, and only retried after the long
  ``quarantine_s`` cooldown.
- **Rolling restart wave** (``pio fleet restart``) — one replica at a
  time: admin-drain on the router → graceful ``/stop`` (terminate as
  fallback) → respawn → wait ready → undrain. After the first replica
  the wave is gated by the router's PR-17 shadow-diff canary: recent
  queries replayed against the restarted replica and a not-yet-restarted
  baseline; a mismatch fraction above the router's threshold aborts the
  wave with the rest of the fleet untouched.

Every recovery path is provable (TensorFlow's nonfatal-failure design,
arXiv:1605.08695 §4.2, same as the rest of ``workflow/faults.py``): the
``supervisor.respawn`` chaos site fires right before each respawn
``Popen`` — an armed error is a failed exec, which counts against the
crash window and re-enters backoff instead of busy-looping.

The supervisor is deliberately synchronous (a daemon thread around
``poll()``): child-process lifecycle is blocking-syscall territory, and
a thread keeps it testable one ``poll()`` at a time with no event loop.
Cross-thread contact with the router is limited to plain field flips
(``set_quarantined`` / ``set_admin_drained``) and
``canary_from_thread`` (``run_coroutine_threadsafe``).
"""

from __future__ import annotations

import atexit
import json
import logging
import random
import subprocess
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass, field

from ..obs.metrics import METRICS
from ..obs.trace import trace_event
from .faults import FAULTS

__all__ = ["SupervisedReplica", "FleetSupervisor"]

log = logging.getLogger(__name__)

_M_DEATHS = METRICS.counter(
    "pio_fleet_supervisor_deaths_total",
    "replica child exits observed by the supervisor (reaped, by "
    "replica; includes failed respawn attempts)",
    labelnames=("replica",))
_M_RESPAWNS = METRICS.counter(
    "pio_fleet_supervisor_respawns_total",
    "replica respawns launched by the supervisor",
    labelnames=("replica",))
_M_QUARANTINED = METRICS.gauge(
    "pio_fleet_supervisor_quarantined",
    "1 while a replica is quarantined for crash-looping",
    labelnames=("replica",))
_M_BACKOFF = METRICS.histogram(
    "pio_fleet_supervisor_backoff_seconds",
    "jittered exponential backoff chosen before each respawn")
_M_RESPAWN_READY = METRICS.histogram(
    "pio_fleet_supervisor_respawn_to_ready_seconds",
    "death detection -> respawned replica reports ready")
_M_WAVES = METRICS.counter(
    "pio_fleet_supervisor_restart_waves_total",
    "rolling restart waves by outcome (ok/canary_abort/failed)",
    labelnames=("outcome",))
_M_CHILDREN = METRICS.gauge(
    "pio_fleet_supervisor_children",
    "replica children currently running under the supervisor")

#: replica lifecycle: pending -> running <-> backoff, with quarantined
#: (crash loop) and restarting (rolling wave) as supervised detours and
#: stopped as the terminal state
_STATES = ("pending", "running", "backoff", "quarantined", "restarting",
           "stopped")


@dataclass
class SupervisedReplica:
    """Supervisor-side view of one replica child process."""

    name: str
    port: int
    url: str
    proc: subprocess.Popen | None = None
    state: str = "pending"
    deaths: deque = field(default_factory=deque)  # monotonic instants
    respawns: int = 0
    backoff_until: float = 0.0
    last_backoff_s: float = 0.0
    quarantined_until: float = 0.0
    awaiting_ready: bool = False
    death_detected: float = 0.0      # feeds respawn-to-ready latency
    spawned_at: float = 0.0
    ready_at: float = 0.0
    last_exit: int | None = None

    def snapshot(self, now: float) -> dict:
        return {
            "name": self.name,
            "port": self.port,
            "url": self.url,
            "pid": self.proc.pid if self.proc is not None else None,
            "state": self.state,
            "deathsInWindow": len(self.deaths),
            "respawns": self.respawns,
            "lastExit": self.last_exit,
            "backoffRemainingS": round(max(0.0, self.backoff_until - now), 3)
            if self.state == "backoff" else 0.0,
            "quarantineRemainingS":
                round(max(0.0, self.quarantined_until - now), 3)
                if self.state == "quarantined" else 0.0,
        }


class FleetSupervisor:
    """Own the replica subprocesses end-to-end (see module doc).

    ``spawn`` is a callable ``(SupervisedReplica) -> Popen`` so the
    CLI hands in a real ``pio deploy`` exec while tests supervise
    fast-booting stubs. Use as a context manager (or call ``start`` /
    ``stop``); ``terminate_all`` also runs at interpreter exit so a
    dying supervisor never strands its brood.
    """

    def __init__(
        self,
        spawn,
        replicas: list[dict],
        *,
        router=None,
        max_respawns: int = 5,
        crash_window_s: float = 60.0,
        quarantine_s: float = 300.0,
        backoff_base_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        poll_interval_s: float = 0.2,
        ready_timeout_s: float = 120.0,
        ready_probe_timeout_s: float = 0.5,
        state_writer=None,
        rng: random.Random | None = None,
    ):
        self.spawn = spawn
        self.replicas: list[SupervisedReplica] = [
            SupervisedReplica(name=str(r["name"]), port=int(r["port"]),
                              url=str(r["url"]).rstrip("/"))
            for r in replicas]
        self.router = router
        self.max_respawns = max(1, int(max_respawns))
        self.crash_window_s = max(0.1, float(crash_window_s))
        self.quarantine_s = max(0.1, float(quarantine_s))
        self.backoff_base_s = max(0.01, float(backoff_base_s))
        self.backoff_cap_s = max(self.backoff_base_s, float(backoff_cap_s))
        self.poll_interval_s = max(0.01, float(poll_interval_s))
        self.ready_timeout_s = max(0.1, float(ready_timeout_s))
        self.ready_probe_timeout_s = max(0.05, float(ready_probe_timeout_s))
        self.state_writer = state_writer
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        for rep in self.replicas:
            _M_QUARANTINED.set(0, replica=rep.name)

    # -- wiring ------------------------------------------------------------
    def replica(self, name: str) -> SupervisedReplica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(name)

    def adopt(self, name: str, proc: subprocess.Popen) -> None:
        """Take ownership of an already-spawned child (the initial
        ``spawn_replicas`` brood from `pio fleet start`)."""
        with self._lock:
            rep = self.replica(name)
            rep.proc = proc
            rep.state = "running"
            rep.awaiting_ready = True
            rep.spawned_at = time.monotonic()
        self._set_children_gauge()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        atexit.register(self.terminate_all)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-supervisor")
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    def __enter__(self) -> "FleetSupervisor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
        self.terminate_all()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("supervisor poll pass failed")
            self._stop.wait(self.poll_interval_s)

    def terminate_all(self, timeout_s: float = 5.0) -> None:
        """Terminate and REAP the whole brood (idempotent; atexit)."""
        with self._lock:
            reps = [r for r in self.replicas
                    if r.proc is not None and r.proc.poll() is None]
            for rep in reps:
                rep.state = "stopped"
                try:
                    rep.proc.terminate()
                except OSError:
                    pass
            deadline = time.monotonic() + timeout_s
            for rep in reps:
                try:
                    rep.proc.wait(
                        timeout=max(0.1, deadline - time.monotonic()))
                except (subprocess.TimeoutExpired, OSError):
                    try:
                        rep.proc.kill()
                        rep.proc.wait(timeout=1.0)
                    except (subprocess.TimeoutExpired, OSError):
                        pass
            for rep in self.replicas:
                if rep.state != "stopped":
                    rep.state = "stopped"
        self._set_children_gauge()

    # -- the control loop --------------------------------------------------
    def poll(self) -> None:
        """One supervision pass over every replica — reap, respawn,
        quarantine, track readiness. Called by the loop thread; also
        directly by tests for deterministic single-stepping."""
        now = time.monotonic()
        with self._lock:
            for rep in self.replicas:
                if rep.state in ("stopped", "restarting"):
                    continue
                if rep.state == "pending":
                    self._respawn(rep, now, initial=True)
                elif rep.state == "running":
                    rc = rep.proc.poll() if rep.proc is not None else 1
                    if rc is not None:
                        self._on_death(rep, rc, now)
                    elif rep.awaiting_ready:
                        self._check_ready(rep, now)
                elif rep.state == "backoff":
                    if now >= rep.backoff_until:
                        self._respawn(rep, now)
                elif rep.state == "quarantined":
                    if now >= rep.quarantined_until:
                        log.info("replica %s quarantine cooldown over; "
                                 "retrying", rep.name)
                        self._respawn(rep, now)
        self._set_children_gauge()

    def _prune_deaths(self, rep: SupervisedReplica, now: float) -> None:
        while rep.deaths and now - rep.deaths[0] > self.crash_window_s:
            rep.deaths.popleft()

    def _on_death(self, rep: SupervisedReplica, rc: int | None,
                  now: float) -> None:
        rep.last_exit = rc
        rep.awaiting_ready = False
        _M_DEATHS.inc(replica=rep.name)
        if rc == 0:
            # a clean exit is operator intent (`pio fleet drain --stop`,
            # a direct /stop), not a crash: respawning would fight the
            # operator, and repeated graceful stops must never
            # accumulate toward quarantining a healthy replica. Only
            # rc != 0 (or a failed exec) enters the crash window.
            rep.state = "stopped"
            rep.death_detected = 0.0
            log.info("replica %s (port %d) exited cleanly; "
                     "not respawning (operator stop)", rep.name, rep.port)
            trace_event("supervisor.stop", replica=rep.name)
            self._write_state()
            return
        rep.deaths.append(now)
        self._prune_deaths(rep, now)
        rep.death_detected = now
        if rc is not None:
            log.warning("replica %s (port %d) exited rc=%s "
                        "(death %d/%d in %.0fs window)",
                        rep.name, rep.port, rc, len(rep.deaths),
                        self.max_respawns, self.crash_window_s)
        if len(rep.deaths) >= self.max_respawns:
            self._quarantine(rep, now)
            return
        # the exponent is deaths-IN-WINDOW, not a consecutive counter:
        # a crash loop that briefly reaches ready between deaths still
        # escalates its backoff until the sliding window forgets
        delay = self._backoff_delay(len(rep.deaths))
        rep.last_backoff_s = delay
        rep.backoff_until = now + delay
        rep.state = "backoff"
        _M_BACKOFF.record(delay)
        trace_event("supervisor.death", replica=rep.name, rc=rc,
                    backoff_s=round(delay, 3), deaths=len(rep.deaths))
        log.info("replica %s respawn scheduled in %.2fs", rep.name, delay)

    def _backoff_delay(self, attempt: int) -> float:
        """base * 2^(n-1) capped, ±20% jitter. The jitter band is
        narrower than the doubling, so successive delays still grow
        strictly until the cap — provable backoff, de-correlated
        respawns."""
        raw = min(self.backoff_cap_s,
                  self.backoff_base_s * (2.0 ** max(0, attempt - 1)))
        return raw * (0.8 + 0.4 * self._rng.random())

    def _quarantine(self, rep: SupervisedReplica, now: float) -> None:
        rep.state = "quarantined"
        rep.quarantined_until = now + self.quarantine_s
        _M_QUARANTINED.set(1, replica=rep.name)
        log.error("replica %s (port %d) is CRASH-LOOPING "
                  "(%d deaths in %.0fs): quarantined for %.0fs",
                  rep.name, rep.port, len(rep.deaths),
                  self.crash_window_s, self.quarantine_s)
        trace_event("supervisor.quarantine", replica=rep.name,
                    deaths=len(rep.deaths), cooldown_s=self.quarantine_s)
        if self.router is not None:
            self.router.set_quarantined(rep.name, True)
        self._write_state()

    def _respawn(self, rep: SupervisedReplica, now: float,
                 initial: bool = False) -> None:
        """Launch (or relaunch) the child on its ORIGINAL port. A
        failed exec counts against the crash window — backoff, never
        a busy loop."""
        was_quarantined = rep.state == "quarantined"
        try:
            FAULTS.fire("supervisor.respawn")
            proc = self.spawn(rep)
        except Exception as e:  # noqa: BLE001 — failed exec == a death
            log.warning("respawn of %s failed: %r", rep.name, e)
            self._on_death(rep, None, now)
            return
        rep.proc = proc
        rep.state = "running"
        rep.awaiting_ready = True
        rep.spawned_at = now
        if not initial:
            rep.respawns += 1
            _M_RESPAWNS.inc(replica=rep.name)
        if was_quarantined:
            _M_QUARANTINED.set(0, replica=rep.name)
            if self.router is not None:
                self.router.set_quarantined(rep.name, False)
        # every spawn changes the child pid — republish the state file
        # so `pio fleet status` and staleness detection see live pids
        self._write_state()
        trace_event("supervisor.respawn", replica=rep.name,
                    pid=proc.pid, initial=initial)
        log.info("replica %s %sspawned on port %d (pid %d)",
                 rep.name, "" if initial else "re", rep.port, proc.pid)

    def _check_ready(self, rep: SupervisedReplica, now: float) -> None:
        if not self._probe_ready(rep.url):
            if now - rep.spawned_at > self.ready_timeout_s:
                log.warning("replica %s not ready after %.0fs; "
                            "recycling", rep.name, self.ready_timeout_s)
                try:
                    rep.proc.kill()
                except OSError:
                    pass
            return
        rep.awaiting_ready = False
        rep.ready_at = now
        if rep.death_detected > 0.0:
            _M_RESPAWN_READY.record(now - rep.death_detected)
            trace_event("supervisor.ready", replica=rep.name,
                        respawn_to_ready_s=round(now - rep.death_detected,
                                                 3))
            rep.death_detected = 0.0

    def _probe_ready(self, url: str) -> bool:
        try:
            with urllib.request.urlopen(
                    f"{url}/health.json",
                    timeout=self.ready_probe_timeout_s) as resp:
                body = json.loads(resp.read())
            return bool(body.get("ready", resp.status == 200))
        except (urllib.error.URLError, OSError, ValueError):
            return False

    # -- rolling restart wave (`pio fleet restart`) ------------------------
    def rolling_restart(self, canary_sample: int | None = None,
                        drain_timeout_s: float = 15.0) -> dict:
        """Drain → restart → re-ready ONE replica at a time; after the
        first restarted replica, gate the rest of the wave on the
        router's shadow-diff canary against a not-yet-restarted
        baseline. Aborting leaves the remaining replicas untouched (the
        rollback is not doing the rollout)."""
        router = self.router
        sample = (router.canary_sample if canary_sample is None and
                  router is not None else int(canary_sample or 0))
        wave: list[dict] = []
        outcome = "ok"
        canary: dict | None = None
        with self._lock:
            targets = [r for r in self.replicas
                       if r.state in ("running", "backoff")]
        for i, rep in enumerate(targets):
            t0 = time.monotonic()
            with self._lock:
                rep.state = "restarting"  # poll() must not count this exit
            if router is not None:
                router.set_admin_drained(rep.name, True)
            try:
                self._graceful_stop(rep, drain_timeout_s)
                with self._lock:
                    self._respawn(rep, time.monotonic())
                    rep.state = "restarting"  # keep poll() hands-off
                if not self._await_ready(rep):
                    raise TimeoutError(
                        f"{rep.name} not ready within "
                        f"{self.ready_timeout_s}s after restart")
            except Exception as e:  # noqa: BLE001 — abort, undrain, report
                outcome = "failed"
                wave.append({"replica": rep.name, "ok": False,
                             "error": str(e)})
                with self._lock:
                    rep.state = "running"
                if router is not None:
                    router.set_admin_drained(rep.name, False)
                break
            with self._lock:
                rep.state = "running"
                rep.awaiting_ready = False
            if router is not None:
                router.set_admin_drained(rep.name, False)
            wave.append({"replica": rep.name, "ok": True,
                         "restartS": round(time.monotonic() - t0, 3)})
            baseline = next((r for r in targets[i + 1:]), None)
            if (i == 0 and sample > 0 and router is not None
                    and baseline is not None):
                canary = router.canary_from_thread(rep.name, baseline.name,
                                                  sample)
                if (canary.get("mismatchFraction", 0.0)
                        > router.canary_max_mismatch):
                    outcome = "canary_abort"
                    break
        _M_WAVES.inc(outcome=outcome)
        trace_event("supervisor.restart_wave", outcome=outcome,
                    restarted=sum(1 for w in wave if w.get("ok")))
        report = {"outcome": outcome, "wave": wave,
                  "restarted": sum(1 for w in wave if w.get("ok")),
                  "replicas": len(targets)}
        if canary is not None:
            report["canary"] = canary
        return report

    def _graceful_stop(self, rep: SupervisedReplica,
                       drain_timeout_s: float) -> None:
        proc = rep.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            with urllib.request.urlopen(f"{rep.url}/stop",
                                        timeout=2.0):
                pass
        except (urllib.error.URLError, OSError, ValueError):
            pass  # dead or deaf: escalate to terminate below
        try:
            proc.wait(timeout=drain_timeout_s)
            return
        except subprocess.TimeoutExpired:
            pass
        try:
            proc.terminate()
            proc.wait(timeout=drain_timeout_s)
        except (subprocess.TimeoutExpired, OSError):
            try:
                proc.kill()
                proc.wait(timeout=2.0)
            except (subprocess.TimeoutExpired, OSError):
                pass

    def _await_ready(self, rep: SupervisedReplica) -> bool:
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            if rep.proc is not None and rep.proc.poll() is not None:
                return False
            if self._probe_ready(rep.url):
                return True
            time.sleep(0.05)
        return False

    # -- views -------------------------------------------------------------
    def _set_children_gauge(self) -> None:
        _M_CHILDREN.set(sum(
            1 for r in self.replicas
            if r.proc is not None and r.proc.poll() is None))

    def _write_state(self) -> None:
        if self.state_writer is None:
            return
        try:
            self.state_writer(self)
        except Exception:  # noqa: BLE001 — state file is advisory
            log.exception("fleet state rewrite failed")

    def status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "maxRespawns": self.max_respawns,
                "crashWindowS": self.crash_window_s,
                "quarantineS": self.quarantine_s,
                "replicas": [r.snapshot(now) for r in self.replicas],
            }
