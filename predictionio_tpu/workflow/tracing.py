"""Structured per-phase timing + jax.profiler trace capture.

The reference has no tracing beyond logging — Spark's UI is its implicit
profiler (SURVEY.md §5). The TPU build surfaces the equivalents natively:

- ``phase_timer``: wall-clock per pipeline phase (read/prepare/train-algo),
  logged structured and accumulated on the Context so `pio train -v`
  prints a phase breakdown at the end — the role of Spark's stage view.
- ``maybe_profile``: wraps a region in ``jax.profiler.trace`` when a
  trace directory is set (``pio train --profile-dir``); the output loads
  in TensorBoard/XProf (device timelines, HLO cost analysis).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time

log = logging.getLogger("predictionio_tpu.workflow")

__all__ = [
    "maybe_profile", "phase_timer", "phase_report", "reset_phases",
    "phase_times_json",
]


def reset_phases(ctx) -> None:
    """Start a run (or a supervised RETRY attempt) with a clean slate.

    ``phase_times`` accumulates on the Context object; a retried attempt
    re-runs every phase, so without this reset the breakdown would
    double-count and the persisted record would blame phases for time
    they never spent in the successful attempt."""
    ctx.phase_times = []


def phase_times_json(ctx) -> str:
    """The phase breakdown as a compact JSON list of [phase, seconds]
    pairs — the shape persisted into the EngineInstance record."""
    times = getattr(ctx, "phase_times", None) or []
    return json.dumps([[p, round(dt, 6)] for p, dt in times])


@contextlib.contextmanager
def phase_timer(ctx, phase: str):
    """Time one pipeline phase; record on ctx.phase_times + log."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        times = getattr(ctx, "phase_times", None)
        if times is None:
            times = ctx.phase_times = []
        times.append((phase, dt))
        log.info("phase %-24s %8.3fs", phase, dt)


def phase_report(ctx) -> str:
    """One-line breakdown of every timed phase, longest first."""
    times = getattr(ctx, "phase_times", None) or []
    total = sum(dt for _, dt in times)
    parts = ", ".join(
        f"{p}={dt:.2f}s" for p, dt in sorted(times, key=lambda x: -x[1]))
    return f"total {total:.2f}s ({parts})" if parts else "no phases timed"


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """jax.profiler.trace when a directory is given; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    log.info("capturing jax profiler trace -> %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield
    log.info("profiler trace written to %s (open with TensorBoard/XProf)",
             trace_dir)
