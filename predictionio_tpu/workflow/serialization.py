"""Model (de)serialization for the model store.

The reference Kryo-serializes the trained model list into MODELDATA
(reference: core/src/main/scala/io/prediction/workflow/CoreWorkflow.scala:
69-74 and CreateServer.scala:61-75 KryoInstantiator). Here models are
pytrees; jax Arrays are pulled to host numpy before pickling so blobs are
device-independent, and algorithms whose ``persist_model`` is False are
stored as a ``PersistentModelManifest`` (className marker) or a retrain
marker — the reference's three persistence paths (Engine.makeSerializable
Models, Engine.scala:260-278; PersistentModelManifest.scala).
"""

from __future__ import annotations

import dataclasses
import io
import pickle
import re
from typing import Any, Sequence

__all__ = [
    "PersistentModelManifest", "RetrainMarker", "serialize_models",
    "deserialize_models",
]


@dataclasses.dataclass(frozen=True)
class PersistentModelManifest:
    """Marker stored in place of a custom-persisted model
    (reference: workflow/PersistentModelManifest.scala)."""

    class_name: str
    module: str


@dataclasses.dataclass(frozen=True)
class RetrainMarker:
    """Marker for non-persistable models: retrain at deploy
    (reference: Engine.prepareDeploy, Engine.scala:186-208)."""

    algorithm_class: str


def _to_host(tree: Any) -> Any:
    """Pull any jax Arrays in a pytree down to numpy for pickling."""
    try:
        import jax
        import numpy as np
    except ImportError:  # storage-only installs
        return tree

    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(conv, tree)


#: dir-scoped engine modules (workflow/core_workflow.py:
#: _import_engine_scoped) carry a `_pio_engine_<dirhash>_` name prefix;
#: blobs must never depend on it — the hash changes whenever the engine
#: dir's absolute path does (another host, a moved project)
_SCOPED_RE = re.compile(r"^_pio_engine_[0-9a-f]{10}_")


def plain_module_name(name: str) -> str:
    """Strip the dir-scoped prefix: stable across hosts/paths."""
    return _SCOPED_RE.sub("", name)


class _EngineScopedUnpickler(pickle.Unpickler):
    """Unpickler that re-resolves engine-module classes against a given
    engine dir. A blob may reference a module as the plain name (a pre-
    scoping blob, or another host's process) or as a scoped name whose dir
    hash no longer matches — both re-import from ``engine_dir``."""

    def __init__(self, file, engine_dir=None):
        super().__init__(file)
        self._engine_dir = engine_dir

    def find_class(self, module, name):
        # engine-dir FIRST: a plain sibling-module name (e.g.
        # 'data_source') would otherwise resolve by sys.path order and
        # could bind another engine's same-named file when several
        # engine dirs are loaded in one process
        if self._engine_dir is not None:
            try:
                from .core_workflow import _import_engine_scoped

                mod = _import_engine_scoped(
                    self._engine_dir, plain_module_name(module))
                if mod is not None:
                    obj = mod
                    for part in name.split("."):
                        obj = getattr(obj, part)
                    return obj
            except Exception:
                pass  # fall through to the normal resolution
        return super().find_class(module, name)


def serialize_models(models: Sequence[Any]) -> bytes:
    buf = io.BytesIO()
    pickle.dump([_to_host(m) for m in models], buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def deserialize_models(blob: bytes, *, engine_dir=None) -> list[Any]:
    return _EngineScopedUnpickler(io.BytesIO(blob), engine_dir).load()
