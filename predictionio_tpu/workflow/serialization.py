"""Model (de)serialization for the model store.

The reference Kryo-serializes the trained model list into MODELDATA
(reference: core/src/main/scala/io/prediction/workflow/CoreWorkflow.scala:
69-74 and CreateServer.scala:61-75 KryoInstantiator). Here models are
pytrees; jax Arrays are pulled to host numpy before pickling so blobs are
device-independent, and algorithms whose ``persist_model`` is False are
stored as a ``PersistentModelManifest`` (className marker) or a retrain
marker — the reference's three persistence paths (Engine.makeSerializable
Models, Engine.scala:260-278; PersistentModelManifest.scala).
"""

from __future__ import annotations

import dataclasses
import io
import pickle
from typing import Any, Sequence

__all__ = [
    "PersistentModelManifest", "RetrainMarker", "serialize_models",
    "deserialize_models",
]


@dataclasses.dataclass(frozen=True)
class PersistentModelManifest:
    """Marker stored in place of a custom-persisted model
    (reference: workflow/PersistentModelManifest.scala)."""

    class_name: str
    module: str


@dataclasses.dataclass(frozen=True)
class RetrainMarker:
    """Marker for non-persistable models: retrain at deploy
    (reference: Engine.prepareDeploy, Engine.scala:186-208)."""

    algorithm_class: str


def _to_host(tree: Any) -> Any:
    """Pull any jax Arrays in a pytree down to numpy for pickling."""
    try:
        import jax
        import numpy as np
    except ImportError:  # storage-only installs
        return tree

    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(conv, tree)


def serialize_models(models: Sequence[Any]) -> bytes:
    buf = io.BytesIO()
    pickle.dump([_to_host(m) for m in models], buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def deserialize_models(blob: bytes) -> list[Any]:
    return pickle.loads(blob)
