"""`pio tune`: mesh-packed hyperparameter sweeps (ISSUE 15).

The reference's fifth DASE letter — Evaluation — tunes by looping
EngineParams variants through full re-trains (EvaluationWorkflow.scala;
MLlib CrossValidation does the same serial loop). On TPU that loop is
exactly backwards: ALX (arXiv:2112.02194) shows the wins come from
keeping the chips saturated, and a rank/λ/α grid of dozens of SMALL
independent ALS trains is the ideal many-small-problems saturation
workload. This module packs the whole grid into one compiled program:

- ``TuneSupervisor`` takes an EngineParams grid (typically from an
  ``EngineParamsGenerator``), wraps the engine in ``FastEvalEngine`` so
  the data/prepare stages memoize ONCE across every trial, and — when
  every trial is a single ALS algorithm exposing the ``als_config()``
  hook over Ratings folds — trains all trials per fold via
  ``models/als.train_als_grid`` (per-rank vmapped λ/α lanes, one jitted
  dispatch per iteration, bitwise-equal to serial training) and seeds
  the resulting models into the FastEvalEngine cache, so each trial's
  ``eval`` scores straight from cache.
- Each trial's score-and-record body runs under a PR-8
  ``TrainSupervisor`` (classify/retry): a diverging or faulted trial
  becomes a FAILED leaderboard row — it never kills the grid. The
  ``tune.trial`` chaos site proves that isolation.
- ``run_tune`` drives the end-to-end pipeline: tune -> train the
  winner on the FULL training data (``run_train`` — supervised,
  persisted, heartbeated) -> stamp the leaderboard into the winner's
  ``EngineInstance.tuning`` and its eval result into
  ``evaluator_results`` -> emit the eval-gate decision against the
  incumbent instance (same promote-iff-no-regression semantics as the
  PR-10 streaming gate: candidate >= baseline - gate). ``pio tune
  --deploy`` deploys only on promote.

Per-trial convergence streams into ``ConvergenceTracker`` under
``source="tune:<trial>"``; the grid emits ``pio_tune_*`` metrics (see
docs/operations.md's catalog).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import time
from typing import Any, Sequence

from ..controller.engine import Engine
from ..controller.evaluation import MetricEvaluatorResult, MetricScores
from ..controller.fast_eval import FastEvalEngine
from ..controller.metric import Metric
from ..controller.params import EngineParams, params_to_json
from ..obs.metrics import METRICS
from ..obs.training import TRAINING
from ..storage import Storage
from ..storage.frame import Ratings
from ..storage.metadata import EngineInstance
from .context import Context
from .core_workflow import run_train, stamp_evaluator_results
from .faults import FAULTS
from .supervisor import TrainSupervisor

log = logging.getLogger("predictionio_tpu.tuning")

__all__ = ["TrialResult", "TuneResult", "TuneSupervisor", "run_tune",
           "tune_gate_decision"]

_M_TRIALS = METRICS.counter(
    "pio_tune_trials_total",
    "tuning trials by terminal status (workflow/tuning.py; FAILED rows "
    "stay on the leaderboard — they never kill the grid)",
    labelnames=("status",))
for _s in ("COMPLETED", "FAILED"):
    _M_TRIALS.labels(status=_s).inc(0)
_M_GRID_S = METRICS.histogram(
    "pio_tune_grid_seconds",
    "wall clock of one packed grid train: every trial x every eval fold "
    "through train_als_grid (excludes scoring)")
_M_TRIAL_S = METRICS.histogram(
    "pio_tune_trial_seconds",
    "per-trial supervised score-and-record wall clock (cache-served "
    "model + metric calculation; includes retries)")
_M_BEST = METRICS.gauge(
    "pio_tune_best_score",
    "primary-metric score of the current tuning leaderboard winner")


@dataclasses.dataclass
class TrialResult:
    """One leaderboard row: a trial's params, terminal status and score.
    ``status`` is COMPLETED or FAILED — a FAILED trial keeps its row
    (with ``error``) so the operator sees WHICH config diverged."""

    index: int
    params: EngineParams
    status: str
    score: Any = None
    other_scores: tuple = ()
    error: str = ""
    attempts: int = 1
    seconds: float = 0.0
    convergence: list = dataclasses.field(default_factory=list)

    def to_row(self) -> dict:
        return {
            "trial": self.index,
            "status": self.status,
            "score": self.score,
            "otherScores": list(self.other_scores),
            "error": self.error,
            "attempts": self.attempts,
            "seconds": round(self.seconds, 3),
            "algorithmsParams":
                self.params.to_json_dict().get("algorithmsParams"),
            "convergence": self.convergence,
        }


@dataclasses.dataclass
class TuneResult:
    """A whole sweep's outcome: every trial's row plus the winner."""

    trials: list[TrialResult]
    best_idx: int  # winning TRIAL index (trials[i].index), -1 if none
    metric_header: str
    other_metric_headers: tuple[str, ...]
    lower_is_better: bool
    grid_mode: str  # "vmapped" (packed program) | "serial" (fallback)
    grid_seconds: float = 0.0

    @property
    def winner(self) -> TrialResult | None:
        for t in self.trials:
            if t.index == self.best_idx:
                return t
        return None

    def completed(self) -> list[TrialResult]:
        return [t for t in self.trials if t.status == "COMPLETED"]

    def to_metric_result(self) -> MetricEvaluatorResult:
        """The COMPLETED rows as a MetricEvaluatorResult — the shape
        ``stamp_evaluator_results`` / best.json already speak."""
        done = self.completed()
        if not done:
            raise ValueError("no completed trials to rank")
        scored = [(t.params, MetricScores(t.score, list(t.other_scores)))
                  for t in done]
        bi = next(i for i, t in enumerate(done) if t.index == self.best_idx)
        return MetricEvaluatorResult(
            best_score=scored[bi][1],
            best_engine_params=scored[bi][0],
            best_idx=bi,
            metric_header=self.metric_header,
            other_metric_headers=list(self.other_metric_headers),
            engine_params_scores=scored,
            lower_is_better=self.lower_is_better,
        )

    def leaderboard_json(self) -> str:
        """The ``EngineInstance.tuning`` document (also `/tune.json`)."""
        return json.dumps({
            "metricHeader": self.metric_header,
            "otherMetricHeaders": list(self.other_metric_headers),
            "lowerIsBetter": self.lower_is_better,
            "bestTrial": self.best_idx,
            "gridMode": self.grid_mode,
            "gridSeconds": round(self.grid_seconds, 3),
            "trials": [t.to_row() for t in self.trials],
        }, default=str)

    def pretty_print(self) -> str:
        lines = [f"Tuning leaderboard ({self.metric_header}, "
                 f"{self.grid_mode} grid):"]
        done = sorted(
            self.completed(),
            key=lambda t: t.score if t.score is not None else 0.0,
            reverse=not self.lower_is_better)
        for pos, t in enumerate(done):
            star = "  <== WINNER" if t.index == self.best_idx else ""
            lines.append(
                f"  {pos + 1:2d}. trial #{t.index} "
                f"[{self.metric_header}={t.score}] "
                f"({t.seconds:.2f}s, {t.attempts} attempt(s)){star}")
        for t in self.trials:
            if t.status != "COMPLETED":
                lines.append(f"   -. trial #{t.index} FAILED: {t.error}")
        return "\n".join(lines)


def _prefix_key(ep: EngineParams) -> str:
    """data-source + preparator identity of a variant (the shared-fold
    precondition of the packed grid)."""
    return (params_to_json(ep.data_source_params) + "|"
            + params_to_json(ep.preparator_params))


class TuneSupervisor:
    """Run an EngineParams grid as one mesh-packed program and rank it.

    ``run(ctx, engine_params_list)`` returns a ``TuneResult`` whose
    trials are 1:1 with the input grid, in order. Per-trial failures
    (divergence, injected ``tune.trial`` chaos, metric errors) are
    classified by the PR-8 supervisor — transient ones retry up to
    ``max_retries`` — and a trial that still fails becomes a FAILED row
    without affecting its neighbors.
    """

    def __init__(self, engine: Engine, metric: Metric,
                 other_metrics: Sequence[Metric] = (), *,
                 max_retries: int = 0, retry_backoff_s: float = 0.25,
                 backoff_cap_s: float = 5.0, rng=None):
        self.engine = engine
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.rng = rng
        self.grid_mode = "serial"
        self.grid_seconds = 0.0

    # -- engine wrapping ---------------------------------------------------
    def _wrap(self, engine: Engine) -> Engine:
        try:
            return FastEvalEngine.wrap(engine)
        except ValueError as e:
            log.info("FastEvalEngine unavailable (%s); tuning without "
                     "prefix memoization", e)
            return engine

    # -- packed grid train -------------------------------------------------
    def _grid_configs(self, eng: Engine, eps: list[EngineParams]):
        """Per-trial ALSConfigs when EVERY trial is one ALS algorithm
        exposing the ``als_config()`` hook, else None (serial path)."""
        configs = []
        for ep in eps:
            if len(list(ep.algorithm_params_list)) != 1:
                return None
            _names, algos = eng.make_algorithms(ep)
            hook = getattr(algos[0], "als_config", None)
            if hook is None:
                return None
            configs.append(hook())
        return configs

    def _grid_train(self, ctx, eng: Engine, eps: list[EngineParams]) -> None:
        """Try the packed path: train every trial x every fold via
        ``train_als_grid`` and seed the FastEvalEngine model cache. Any
        incompatibility (multi-algo trials, non-ALS algorithms, mixed
        data-source params, non-Ratings prepared data, incompatible
        configs) falls back to the serial per-trial path — the sweep
        still completes, just without the packed speedup."""
        if not isinstance(eng, FastEvalEngine):
            return
        if len({_prefix_key(ep) for ep in eps}) != 1:
            log.info("grid trials differ in data/prepare params; "
                     "training serially")
            return
        configs = self._grid_configs(eng, eps)
        if configs is None:
            log.info("grid trials are not single-ALS (no als_config hook); "
                     "training serially")
            return
        try:
            prepared = eng._prepared(ctx, eps[0])
            if not prepared:
                return  # no eval folds — scoring will surface the error
            if not all(isinstance(pd, Ratings) for pd, _ei, _qa in prepared):
                log.info("prepared eval data is not Ratings; training "
                         "serially")
                return
            from ..models.als import train_als_grid

            iters = configs[0].iterations
            n_folds, n_trials = len(prepared), len(eps)
            for idx in range(n_trials):
                TRAINING.reset_source(f"tune:{idx}")
                TRAINING.begin(f"tune:{idx}",
                               total_iterations=iters * n_folds)
            t0 = time.perf_counter()
            fold_models = []
            for f, (pd, _ei, _qa) in enumerate(prepared):

                def observe(idx, it, loss, delta, step_s, _f=f):
                    # step_s covers the WHOLE grid dispatch — attribute
                    # an even per-trial share
                    TRAINING.observe(f"tune:{idx}", _f * iters + it,
                                     loss=loss, delta_norm=delta,
                                     step_seconds=step_s / max(1, n_trials))

                fold_models.append(
                    train_als_grid(pd, configs, mesh=ctx.mesh,
                                   observe=observe))
            self.grid_seconds = time.perf_counter() - t0
            _M_GRID_S.record(self.grid_seconds)
            for idx, ep in enumerate(eps):
                eng.seed_models(
                    ep, [[fold_models[f][idx]] for f in range(n_folds)])
            self.grid_mode = "vmapped"
            log.info("packed grid trained: %d trial(s) x %d fold(s) in "
                     "%.2fs", n_trials, n_folds, self.grid_seconds)
        except Exception as e:
            log.warning("packed grid train unavailable (%s: %s); trials "
                        "train serially", type(e).__name__, e)

    # -- per-trial supervised scoring --------------------------------------
    def _score_trial(self, ctx, eng: Engine, idx: int,
                     ep: EngineParams) -> TrialResult:
        src = f"tune:{idx}"
        sup = TrainSupervisor(
            max_retries=self.max_retries,
            retry_backoff_s=self.retry_backoff_s,
            backoff_cap_s=self.backoff_cap_s,
            rng=self.rng)

        def body():
            # chaos site: one trial's failure must become a FAILED
            # leaderboard row, never kill the grid (arm times=1)
            FAULTS.fire("tune.trial")
            folds = eng.eval(ctx, ep)
            if not folds:
                raise ValueError(
                    "data source produced no eval folds — set eval_k >= 2")
            fold_tuples = [(f.eval_info, f.qpa) for f in folds]
            score = self.metric.calculate(ctx, fold_tuples)
            if isinstance(score, float) and not math.isfinite(score):
                raise ValueError(
                    f"trial diverged: {self.metric.header()}={score}")
            others = [m.calculate(ctx, fold_tuples)
                      for m in self.other_metrics]
            return score, others

        t0 = time.perf_counter()
        try:
            score, others = sup.run(body)
            status, err = "COMPLETED", ""
        except Exception as e:
            score, others = None, []
            status, err = "FAILED", f"{type(e).__name__}: {e}"
            log.warning("tune trial %d FAILED after %d attempt(s): %s",
                        idx, sup.attempts, err)
        seconds = time.perf_counter() - t0
        _M_TRIAL_S.record(seconds)
        _M_TRIALS.labels(status=status).inc()
        conv: list = []
        if self.grid_mode == "vmapped":
            TRAINING.finish(src, status)
            conv = TRAINING.summaries(src)
        return TrialResult(index=idx, params=ep, status=status, score=score,
                           other_scores=tuple(others), error=err,
                           attempts=sup.attempts, seconds=seconds,
                           convergence=conv)

    def run(self, ctx, engine_params_list: Sequence[EngineParams]) -> TuneResult:
        eps = list(engine_params_list)
        if not eps:
            raise ValueError("empty EngineParams grid")
        eng = self._wrap(self.engine)
        self._grid_train(ctx, eng, eps)
        trials = [self._score_trial(ctx, eng, idx, ep)
                  for idx, ep in enumerate(eps)]
        done = [t for t in trials if t.status == "COMPLETED"
                and t.score is not None]
        best_idx = -1
        if done:
            best = max(done, key=lambda t: self.metric.compare_key(t.score))
            best_idx = best.index
            try:
                _M_BEST.set(float(best.score))
            except (TypeError, ValueError):
                pass
        result = TuneResult(
            trials=trials,
            best_idx=best_idx,
            metric_header=self.metric.header(),
            other_metric_headers=tuple(m.header()
                                       for m in self.other_metrics),
            lower_is_better=bool(self.metric.lower_is_better),
            grid_mode=self.grid_mode,
            grid_seconds=self.grid_seconds,
        )
        log.info("tuning done: %d/%d trial(s) completed, winner=%s",
                 len(done), len(trials),
                 best_idx if best_idx >= 0 else "none")
        return result


# -- eval-gated promotion ---------------------------------------------------
def _stamped_best_score(inst: EngineInstance | None) -> float | None:
    """The incumbent's primary-metric score, from its stamped eval result
    (or its tuning leaderboard's winner). None = nothing comparable."""
    if inst is None:
        return None
    try:
        doc = json.loads(inst.evaluator_results_json or "null")
        if doc and doc.get("bestScore"):
            return float(doc["bestScore"][0])
    except (ValueError, TypeError):
        pass
    try:
        doc = json.loads(inst.tuning or "null")
        if doc:
            for row in doc.get("trials", ()):
                if row.get("trial") == doc.get("bestTrial"):
                    return float(row["score"])
    except (ValueError, TypeError):
        pass
    return None


def tune_gate_decision(tune: TuneResult, baseline: float | None,
                       eval_gate: float | None) -> dict:
    """Promotion gate with the PR-10 streaming-gate semantics
    (workflow/streaming.py _gate_decision): promote iff the candidate
    does not regress past ``eval_gate`` vs the incumbent's stamped score
    (inequality flipped for lower-is-better metrics). ``eval_gate=None``
    -> ungated (always deploy); no incumbent -> promote."""
    winner = tune.winner
    cand = winner.score if winner is not None else None
    d = {"metric": tune.metric_header, "candidate": cand,
         "baseline": baseline, "threshold": eval_gate}
    if eval_gate is None:
        d["decision"] = "ungated"
    elif cand is None:
        d["decision"] = "hold"
    elif baseline is None:
        d["decision"] = "promote"
    elif tune.lower_is_better:
        d["decision"] = ("promote" if cand <= baseline + eval_gate
                         else "hold")
    else:
        d["decision"] = ("promote" if cand >= baseline - eval_gate
                         else "hold")
    return d


def run_tune(
    engine: Engine,
    engine_params_list: Sequence[EngineParams],
    metric: Metric,
    other_metrics: Sequence[Metric] = (),
    ctx: Context | None = None,
    *,
    engine_id: str = "default",
    engine_version: str = "1",
    engine_variant: str = "default",
    engine_factory: str = "",
    batch: str = "",
    evaluator_class: str = "",
    max_retries: int = 0,
    retry_backoff_s: float = 0.25,
    eval_gate: float | None = None,
    best_json_path: str | None = None,
    train_max_retries: int = 0,
    train_budget_s: float | None = None,
) -> tuple[str, TuneResult, dict]:
    """The whole pipeline: tune the grid, train the WINNER on the full
    training data (supervised + persisted ``run_train``), stamp the
    leaderboard + eval result onto the winner's EngineInstance, and
    return ``(engine_instance_id, TuneResult, gate)`` where ``gate`` is
    the promotion decision vs the incumbent (the instance that was
    latest-completed BEFORE this run). ``pio tune --deploy`` serves the
    new instance only when the gate says promote/ungated."""
    ctx = ctx or Context(mode="Evaluation", batch=batch)
    supervisor = TuneSupervisor(
        engine, metric, other_metrics,
        max_retries=max_retries, retry_backoff_s=retry_backoff_s)
    tune = supervisor.run(ctx, engine_params_list)
    winner = tune.winner
    if winner is None:
        raise RuntimeError(
            "tuning produced no completed trial — nothing to train "
            f"({sum(1 for t in tune.trials if t.status == 'FAILED')} "
            "FAILED)")
    result = tune.to_metric_result()
    if best_json_path:
        with open(best_json_path, "w") as f:
            json.dump(winner.params.to_json_dict(), f, indent=2, default=str)

    # the incumbent BEFORE the winner trains — the baseline the gate
    # compares against
    meta = Storage.get_metadata()
    incumbent = meta.engine_instance_get_latest_completed(
        engine_id, engine_version, engine_variant)
    baseline = _stamped_best_score(incumbent)

    iid = run_train(
        engine, winner.params, None,
        engine_id=engine_id, engine_version=engine_version,
        engine_variant=engine_variant, engine_factory=engine_factory,
        batch=batch, max_retries=train_max_retries,
        train_budget_s=train_budget_s)
    stamp_evaluator_results(iid, result, evaluator_class=evaluator_class,
                            tuning_json=tune.leaderboard_json())
    gate = tune_gate_decision(tune, baseline, eval_gate)
    log.info("tune winner trial #%d trained as instance %s; gate=%s",
             winner.index, iid, gate["decision"])
    return iid, tune, gate
