"""TrainSupervisor: checkpoint-restart supervision for training runs.

On TPUs preemption is the NORMAL failure mode, not the exceptional one —
the runtime yanks devices out from under a healthy run, the process dies
or sees a device-lost error, and production systems are expected to come
back from the latest checkpoint on their own (TensorFlow's nonfatal-
failure design, arXiv:1605.08695 §4.2; Google's ads-ranking training
infrastructure makes the same checkpoint-restart loop its availability
backbone, arXiv:2501.10546). The reference PredictionIO has nothing
here: a crashed `pio train` leaves its EngineInstance stuck at INIT
forever and the operator re-runs by hand.

This module closes that gap with three cooperating pieces:

- ``classify_error``: splits *transient* failures (device-lost /
  preemption / transient-OOM message patterns, injected chaos faults,
  and anything wrapped in ``TransientTrainingError``) from *fatal* ones
  (a ValueError in user code retries forever and never gets better).
  ``BaseException``s that aren't ``Exception``s — KeyboardInterrupt,
  SystemExit — are always fatal: the operator asked the run to die.

- ``TrainSupervisor``: runs a train body under bounded jittered-backoff
  retries. The body is re-invoked whole on a transient failure; resume
  comes from ``TrainCheckpointer.restore_first_valid`` inside the
  algorithm, so a retry continues from the latest durable step instead
  of iteration zero. A daemon heartbeat thread stamps liveness
  (``last_heartbeat``/``attempt``) through a caller-provided callback so
  `pio status` and the reaper can tell a live run from an orphan, and an
  optional wall-clock budget aborts a hung attempt cleanly
  (``TrainBudgetExceeded``) instead of wedging the process — the hung
  worker thread is abandoned as a daemon zombie, the same reclamation
  pattern as the serving watchdog.

- ``reap_orphans``: flips stale-heartbeat INIT instances to ABANDONED.
  Run explicitly via `pio admin reap` or automatically at the start of
  every training run, so the instance table converges on the truth even
  when runs die without a survivor to mark them.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import replace
from datetime import datetime, timezone
from typing import Any, Callable

from .faults import FaultInjected

log = logging.getLogger("predictionio_tpu.workflow.supervisor")

__all__ = [
    "TransientTrainingError", "TrainBudgetExceeded", "classify_error",
    "TrainSupervisor", "reap_orphans", "DEFAULT_STALE_AFTER_S",
    "HostLostError", "BarrierTimeoutError", "CoordinatorUnreachableError",
    "host_heartbeats", "stale_peers", "check_peer_liveness",
    "DEFAULT_PEER_STALE_AFTER_S",
]

#: An INIT instance whose heartbeat (or, lacking one, start time) is
#: older than this is presumed dead and eligible for reaping.
DEFAULT_STALE_AFTER_S = 600.0

#: A peer process whose per-host heartbeat is older than this is
#: presumed dead (much tighter than the reaper's 10 min: peers beat at
#: heartbeat_s≈5 s, and a survivor blocked on a dead peer's barrier
#: should abort the step, not wait for the orphan reaper).
DEFAULT_PEER_STALE_AFTER_S = 60.0


class TransientTrainingError(RuntimeError):
    """Explicit marker: the wrapped failure is retryable. Engine code can
    raise this around errors the pattern classifier can't know about."""


class HostLostError(TransientTrainingError):
    """A peer process of a multi-host run died (stale peer heartbeat, or
    its absence surfaced at a sync point). Transient by construction:
    the supervisor relaunch resumes from the last complete sharded
    manifest, possibly at a different process count."""


class BarrierTimeoutError(TransientTrainingError):
    """A cross-host barrier (checkpoint shard/manifest sync) timed out —
    the classic symptom of a dead or wedged peer. Survivors abort the
    step cleanly and retry/relaunch from the last complete manifest."""


class CoordinatorUnreachableError(TransientTrainingError):
    """The jax.distributed coordinator (or the shared checkpoint
    filesystem standing in for it) stopped answering. Retryable: a
    restarted coordinator re-forms the cluster and training resumes."""


class TrainBudgetExceeded(RuntimeError):
    """The wall-clock budget expired before the run finished."""


#: Message fragments that mark an exception as transient — the
#: device-lost / preemption / capacity vocabulary of TPU & GPU runtimes
#: (compare tensorflow's UnavailableError/AbortedError retry set).
_TRANSIENT_PATTERNS = (
    "device lost",
    "device is lost",
    "device_lost",
    "preempt",            # "preempted", "preemption notice", ...
    "maintenance event",
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "oom",
    "data_loss",
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "connection reset",
    "socket closed",
    "transient",
    # multi-host failure vocabulary: a dead peer / lost coordinator is
    # the preemption of pod-scale training — always worth a relaunch
    # from the last complete sharded manifest
    "barrier timeout",
    "barrier timed out",
    "coordinator unreachable",
    "coordinator disconnected",
    "host lost",
    "peer heartbeat",
    "heartbeat stale",
)


def classify_error(exc: BaseException) -> str:
    """Return ``"transient"`` (worth a supervised retry) or ``"fatal"``.

    KeyboardInterrupt/SystemExit and every other non-``Exception``
    ``BaseException`` are fatal by construction — retrying an operator's
    Ctrl-C would be hostile.
    """
    if not isinstance(exc, Exception):
        return "fatal"
    if isinstance(exc, (TransientTrainingError, FaultInjected)):
        return "transient"
    if isinstance(exc, (MemoryError, ConnectionError, TimeoutError)):
        return "transient"
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(p in msg for p in _TRANSIENT_PATTERNS):
        return "transient"
    return "fatal"


def _utcnow_iso() -> str:
    return datetime.now(timezone.utc).isoformat()


class _Heartbeat:
    """Daemon thread stamping liveness every ``interval_s`` via
    ``on_beat(iso_timestamp, attempt)``; attempt updates take effect on
    the next beat, plus an immediate beat at every set_attempt()."""

    def __init__(self, on_beat: Callable[[str, int], None], interval_s: float):
        self._on_beat = on_beat
        self._interval_s = interval_s
        self._attempt = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="train-heartbeat", daemon=True)

    def start(self) -> None:
        self.beat()
        self._thread.start()

    def set_attempt(self, attempt: int) -> None:
        self._attempt = attempt
        self.beat()

    def beat(self) -> None:
        try:
            self._on_beat(_utcnow_iso(), self._attempt)
        except Exception:
            # liveness stamping must never kill the training run
            log.warning("heartbeat stamp failed", exc_info=True)

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        # join briefly; a daemon thread stuck in a slow stamp can't block
        # run teardown
        self._thread.join(timeout=2.0)


class TrainSupervisor:
    """Retry/heartbeat/budget harness around one training run's body.

    ``run(body)`` invokes ``body()`` up to ``1 + max_retries`` times.
    Transient failures (see ``classify_error``) sleep a jittered
    exponential backoff and re-invoke the body; fatal failures and
    exhausted budgets re-raise immediately. With ``train_budget_s`` set,
    each attempt runs in a worker thread and the overall wall clock is
    enforced across attempts — on expiry the worker is abandoned (daemon
    zombie) and ``TrainBudgetExceeded`` raised.
    """

    def __init__(
        self,
        *,
        max_retries: int = 0,
        retry_backoff_s: float = 1.0,
        backoff_cap_s: float = 60.0,
        train_budget_s: float | None = None,
        heartbeat_s: float = 5.0,
        on_heartbeat: Callable[[str, int], None] | None = None,
        rng: random.Random | None = None,
    ):
        self.max_retries = max(0, int(max_retries))
        self.retry_backoff_s = max(0.0, retry_backoff_s)
        self.backoff_cap_s = backoff_cap_s
        self.train_budget_s = (
            train_budget_s if train_budget_s and train_budget_s > 0 else None)
        self.heartbeat_s = heartbeat_s
        self._on_heartbeat = on_heartbeat
        self._rng = rng or random.Random()
        #: attempts actually started (1-based after run(); exposed for
        #: assertions and the instance record)
        self.attempts = 0
        self.retries_used = 0

    # -- internals ---------------------------------------------------------
    def _backoff(self, retry_index: int) -> float:
        """Jittered exponential backoff: base*2^i capped, scaled by a
        uniform [0.5, 1.0) factor so synchronized preemptees don't
        stampede the scheduler together."""
        raw = min(self.backoff_cap_s, self.retry_backoff_s * (2 ** retry_index))
        return raw * (0.5 + self._rng.random() / 2)

    def _run_attempt(self, body: Callable[[], Any], deadline: float | None) -> Any:
        if deadline is None:
            return body()
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TrainBudgetExceeded(
                f"train budget {self.train_budget_s}s exhausted before "
                f"attempt {self.attempts}")
        holder: dict[str, Any] = {}

        def _target():
            try:
                holder["result"] = body()
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                holder["error"] = e

        t = threading.Thread(target=_target, name="train-attempt", daemon=True)
        t.start()
        t.join(remaining)
        if t.is_alive():
            # abandon the hung attempt — same zombie pattern as the
            # serving watchdog; the daemon thread dies with the process
            raise TrainBudgetExceeded(
                f"train budget {self.train_budget_s}s expired mid-attempt "
                f"{self.attempts}; abandoning the hung training thread")
        if "error" in holder:
            raise holder["error"]
        return holder["result"]

    # -- public ------------------------------------------------------------
    def run(self, body: Callable[[], Any]) -> Any:
        """Run ``body`` under supervision; returns its result or raises
        the final (fatal / budget / retries-exhausted) error."""
        heartbeat = None
        if self._on_heartbeat is not None:
            heartbeat = _Heartbeat(self._on_heartbeat, self.heartbeat_s)
            heartbeat.start()
        deadline = (
            time.monotonic() + self.train_budget_s
            if self.train_budget_s is not None else None)
        try:
            retry = 0
            while True:
                self.attempts += 1
                if heartbeat is not None:
                    heartbeat.set_attempt(self.attempts - 1)
                try:
                    return self._run_attempt(body, deadline)
                except TrainBudgetExceeded:
                    raise
                except BaseException as exc:
                    kind = classify_error(exc)
                    if kind != "transient" or retry >= self.max_retries:
                        if kind == "transient":
                            log.error(
                                "transient training failure, retries "
                                "exhausted (%d/%d): %r",
                                retry, self.max_retries, exc)
                        raise
                    delay = self._backoff(retry)
                    retry += 1
                    self.retries_used = retry
                    log.warning(
                        "transient training failure (attempt %d, retry "
                        "%d/%d), resuming from latest checkpoint in "
                        "%.2fs: %r",
                        self.attempts, retry, self.max_retries, delay, exc)
                    if deadline is not None and (
                            time.monotonic() + delay >= deadline):
                        raise TrainBudgetExceeded(
                            f"train budget {self.train_budget_s}s leaves no "
                            f"room for retry {retry}") from exc
                    time.sleep(delay)
        finally:
            if heartbeat is not None:
                heartbeat.stop()


def _parse_iso(ts: str) -> datetime | None:
    try:
        dt = datetime.fromisoformat(ts)
    except (TypeError, ValueError):
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt


def heartbeat_age_s(instance, *, now: datetime | None = None) -> float | None:
    """Seconds since the instance's last liveness signal (heartbeat, or
    start_time for pre-supervisor records); None when unparseable."""
    now = now or datetime.now(timezone.utc)
    last = _parse_iso(instance.last_heartbeat) if instance.last_heartbeat else None
    if last is None:
        last = instance.start_time
        if last.tzinfo is None:
            last = last.replace(tzinfo=timezone.utc)
    try:
        return (now - last).total_seconds()
    except TypeError:
        return None


def host_heartbeats(instance) -> dict[int, dict]:
    """Per-process liveness stamps from the instance record:
    ``{process_id: {"ts": iso, "attempt": int, ...}}``. Empty for
    single-host / pre-elastic records or unparseable JSON — liveness
    introspection must never throw."""
    import json

    raw = getattr(instance, "host_heartbeats", "") or ""
    if not raw:
        return {}
    try:
        parsed = json.loads(raw)
        return {int(k): dict(v) for k, v in parsed.items()}
    except (ValueError, TypeError, AttributeError):
        return {}


def stale_peers(
    instance,
    *,
    num_processes: int,
    stale_after_s: float = DEFAULT_PEER_STALE_AFTER_S,
    self_id: int | None = None,
    now: datetime | None = None,
) -> list[int]:
    """Process ids of peers presumed dead: never-stamped or stale-stamped
    entries in the instance's per-host heartbeat map. ``self_id`` is
    excluded — a process never declares itself lost."""
    now = now or datetime.now(timezone.utc)
    beats = host_heartbeats(instance)
    out = []
    for pid in range(num_processes):
        if pid == self_id:
            continue
        entry = beats.get(pid)
        ts = _parse_iso(entry.get("ts", "")) if entry else None
        if ts is None or (now - ts).total_seconds() >= stale_after_s:
            out.append(pid)
    return out


def check_peer_liveness(
    instance,
    *,
    num_processes: int,
    stale_after_s: float = DEFAULT_PEER_STALE_AFTER_S,
    self_id: int | None = None,
    now: datetime | None = None,
) -> None:
    """Raise ``HostLostError`` (transient) when any peer's heartbeat in
    the instance record has gone stale — the survivor-side detection of
    a dead worker, checked between steps so the surviving processes
    abort cleanly instead of wedging on the next barrier."""
    dead = stale_peers(instance, num_processes=num_processes,
                       stale_after_s=stale_after_s, self_id=self_id, now=now)
    if dead:
        raise HostLostError(
            f"host lost: peer heartbeat stale (> {stale_after_s:.0f}s) for "
            f"process(es) {dead} of {num_processes}; aborting step — "
            "relaunch resumes from the last complete sharded manifest")


def reap_orphans(
    meta,
    *,
    stale_after_s: float = DEFAULT_STALE_AFTER_S,
    dry_run: bool = False,
    now: datetime | None = None,
) -> list:
    """Flip INIT instances with a stale heartbeat to ABANDONED; returns
    the instances that were (or with ``dry_run`` would be) reaped.

    An INIT row whose supervisor is alive beats at ``heartbeat_s``
    intervals, so anything quiet for ``stale_after_s`` (default 10 min)
    is an orphan from a process that died without marking itself.
    """
    now = now or datetime.now(timezone.utc)
    reaped = []
    for inst in meta.engine_instance_get_by_status("INIT"):
        age = heartbeat_age_s(inst, now=now)
        if age is None or age < stale_after_s:
            continue
        reaped.append(inst)
        if dry_run:
            continue
        meta.engine_instance_update(
            replace(inst, status="ABANDONED", end_time=now))
        log.warning(
            "reaped orphan engine instance %s (INIT, last liveness %.0fs "
            "ago) -> ABANDONED", inst.id, age)
    return reaped
