"""CoreWorkflow: orchestrate one training or evaluation run.

Analog of reference ``CoreWorkflow`` (core/src/main/scala/io/prediction/
workflow/CoreWorkflow.scala:42-150) + the engine-factory resolution part of
``CreateWorkflow``/``WorkflowUtils`` (workflow/CreateWorkflow.scala:141-277,
WorkflowUtils.scala:60-127): write the instance record (INIT), run the
engine, persist models, flip status to COMPLETED/EVALCOMPLETED.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import logging
import sys
import traceback
from datetime import datetime, timezone
from typing import Any, Sequence

from ..controller.components import PersistentModel
from ..controller.engine import Engine, TrainResult
from ..controller.evaluation import Evaluation, MetricEvaluator, MetricEvaluatorResult
from ..controller.params import EngineParams, params_to_json
from ..obs.training import TRAINING
from ..storage import EngineInstance, EvaluationInstance, Model, Storage
from .context import Context
from .faults import FAULTS
from .supervisor import DEFAULT_STALE_AFTER_S, TrainSupervisor, reap_orphans
from .serialization import (
    PersistentModelManifest,
    RetrainMarker,
    deserialize_models,
    plain_module_name,
    serialize_models,
)

log = logging.getLogger("predictionio_tpu.workflow")

#: engine dir -> its sibling .py stems, registered on first scoped load —
#: the basis for the (once-per-pair) sibling-name collision warning
_SCOPED_ENGINE_DIRS: dict = {}

__all__ = [
    "resolve_attr", "resolve_engine_factory", "run_train", "run_evaluation",
    "stamp_evaluator_results", "prepare_deploy", "ModelIntegrityError",
]


class ModelIntegrityError(RuntimeError):
    """A stored model blob failed its checksum at deploy time."""


def _import_engine_scoped(engine_dir, mod_name: str):
    """Import ``mod_name`` from ``engine_dir`` under a dir-unique FLAT
    module name (``_pio_engine_<dirhash>_<name>``), so that two engines
    whose modules share a name — every template calls its module
    ``engine`` — coexist in one process. This replaces the old permanent
    ``sys.path`` prepend, which made a second engine's ``import engine``
    silently resolve to the first engine's code.

    The flat (dot-free) name keeps pickle round-trips working: classes
    defined in the module carry it as ``__module__``, and unpickling
    re-imports it straight from ``sys.modules`` with no parent package
    needed (serialization.py additionally re-resolves names against the
    engine dir, so blobs survive a moved project). Returns None when
    ``engine_dir`` has no such module (caller falls back to a regular
    import).

    Sibling-module semantics: imports the module body makes eagerly are
    engine-correct (the dir is FIRST on sys.path during exec, and the
    plain-named entries are evicted afterwards); the dir then stays
    APPENDED to sys.path so lazy imports at predict/serve time still
    resolve. With several engines whose *siblings* share names, a lazy
    sibling import binds by sys.path order — that hazard is DETECTED at
    load time: when a newly loaded engine dir carries sibling .py names
    that an earlier-loaded engine dir also has, a warning names the
    collisions so engine authors move those imports into the module body
    (eager imports are always engine-correct).
    """
    import hashlib
    import importlib.util
    from pathlib import Path

    top, _, rest = mod_name.partition(".")
    d = Path(engine_dir).resolve()
    file = d / f"{top}.py"
    pkg = d / top / "__init__.py"
    if not file.exists() and not pkg.exists():
        return None
    if d not in _SCOPED_ENGINE_DIRS:
        # one glob per NEW dir; collision pairs warn once (repeat resolves
        # of already-registered engines cost nothing and stay quiet)
        siblings = frozenset(p.stem for p in d.glob("*.py")) - {top}
        for prev, prev_sibs in _SCOPED_ENGINE_DIRS.items():
            clash = siblings & prev_sibs
            if clash:
                log.warning(
                    "engine dirs %s and %s both define sibling module(s) "
                    "%s: a LAZY `import <name>` at predict/serve time "
                    "binds by sys.path order and may load the other "
                    "engine's file — import siblings at engine-module "
                    "top level instead", d, prev, sorted(clash))
        _SCOPED_ENGINE_DIRS[d] = siblings
    key = hashlib.sha1(str(d).encode()).hexdigest()[:10]
    uniq_top = f"_pio_engine_{key}_{top}"
    if uniq_top not in sys.modules:
        if file.exists():
            spec = importlib.util.spec_from_file_location(uniq_top, file)
        else:
            spec = importlib.util.spec_from_file_location(
                uniq_top, pkg, submodule_search_locations=[str(d / top)])
        module = importlib.util.module_from_spec(spec)
        sys.modules[uniq_top] = module
        # engine-dir on sys.path ONLY while the module body executes, so
        # it can import sibling helper files
        sys.path.insert(0, str(d))
        try:
            spec.loader.exec_module(module)
        except BaseException:
            sys.modules.pop(uniq_top, None)
            raise
        finally:
            try:
                sys.path.remove(str(d))
            except ValueError:
                pass
            if str(d) not in sys.path:
                sys.path.append(str(d))  # lazy serve-time imports
            # sibling modules the body imported by plain name (e.g.
            # `from data_source import X`) were cached under that plain
            # name — evict them so another engine's same-named sibling
            # loads ITS file; the importer keeps its direct references
            for name, m in list(sys.modules.items()):
                f = getattr(m, "__file__", None)
                if (f and "." not in name
                        and not name.startswith("_pio_engine_")
                        and Path(f).parent == d):
                    sys.modules.pop(name, None)
    if rest:
        return importlib.import_module(f"{uniq_top}.{rest}")
    return sys.modules[uniq_top]


def resolve_attr(path: str, *, engine_dir=None) -> Any:
    """'pkg.module.Attr' or 'pkg.module:Attr' -> attribute. The analog of
    WorkflowUtils.getEngine's object/class reflection (WorkflowUtils.scala:
    60-99) with explicit module paths instead of classpath scanning.

    With ``engine_dir``, modules found in that directory are imported
    under a dir-unique name (see _import_engine_scoped) so multiple
    engines coexist in-process; other module paths import normally."""
    if ":" in path:
        mod_name, attr = path.split(":", 1)
    else:
        mod_name, _, attr = path.rpartition(".")
    if not mod_name:
        raise ValueError(f"cannot resolve {path!r}: need 'module.Attr'")
    module = None
    if engine_dir is not None:
        module = _import_engine_scoped(engine_dir, mod_name)
    if module is None:
        module = importlib.import_module(mod_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def resolve_engine_factory(path: str, *, engine_dir=None) -> Engine:
    """Resolve an engineFactory string to an Engine instance. Accepts: an
    EngineFactory subclass, an instance, a function returning an Engine,
    or an Engine object."""
    obj = resolve_attr(path, engine_dir=engine_dir)
    if isinstance(obj, Engine):
        return obj
    candidates = []
    apply = getattr(obj, "apply", None)
    if apply is not None:
        candidates.append(apply)  # EngineFactory class w/ static apply, or instance
        if isinstance(obj, type):
            candidates.append(lambda: obj().apply())
    if callable(obj):
        candidates.append(obj)
    for make in candidates:
        try:
            result = make()
        except TypeError:
            continue
        if isinstance(result, Engine):
            return result
    raise TypeError(f"{path!r} did not yield an Engine (got {obj!r})")


def _now() -> datetime:
    return datetime.now(timezone.utc)


def _params_field(pair: tuple[str, Any]) -> str:
    name, params = pair
    return json.dumps({"name": name, "params": json.loads(params_to_json(params))})


def _algo_params_field(pairs: Sequence[tuple[str, Any]]) -> str:
    return json.dumps(
        [{"name": n, "params": json.loads(params_to_json(p))} for n, p in pairs]
    )


def _persistable(result: TrainResult, instance_id: str) -> list[Any]:
    """Apply the three persistence paths per algorithm
    (Engine.makeSerializableModels, Engine.scala:260-278)."""
    out = []
    for algo, model, name in zip(result.algorithms, result.models, result.algorithm_names):
        if isinstance(model, PersistentModel):
            saved = model.save(instance_id, algo.params)
            if saved:
                out.append(
                    PersistentModelManifest(
                        class_name=type(model).__name__,
                        # plain name: the dir-scoped prefix embeds the
                        # engine dir's path hash, which must not leak
                        # into durable blobs (serialization.py)
                        module=plain_module_name(type(model).__module__),
                    )
                )
            else:
                out.append(RetrainMarker(algorithm_class=type(algo).__name__))
        elif algo.persist_model:
            out.append(model)
        else:
            out.append(RetrainMarker(algorithm_class=type(algo).__name__))
    return out


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    ctx: Context | None = None,
    *,
    engine_id: str = "default",
    engine_version: str = "1",
    engine_variant: str = "default",
    engine_factory: str = "",
    batch: str = "",
    env: dict | None = None,
    max_retries: int = 0,
    retry_backoff_s: float = 1.0,
    train_budget_s: float | None = None,
    heartbeat_s: float = 5.0,
    reap_stale_after_s: float = DEFAULT_STALE_AFTER_S,
    process_id: int = 0,
    num_processes: int = 1,
) -> str:
    """Train and persist; returns the engine instance id
    (CoreWorkflow.runTrain, CoreWorkflow.scala:42-94).

    The body runs under a ``TrainSupervisor``: transient failures
    (preemption / device-lost / injected chaos faults) are retried up to
    ``max_retries`` times with jittered backoff, resuming from the
    latest ``TrainCheckpointer`` step; a heartbeat stamps
    ``last_heartbeat``/``attempt`` into the instance record; and
    ``train_budget_s`` (None = unlimited) bounds the whole run's wall
    clock, aborting cleanly (status ABORTED) instead of hanging. Stale
    INIT orphans from previous dead runs are reaped first.

    Elastic multi-host runs pass ``process_id``/``num_processes``: every
    heartbeat then also stamps this process's entry in the instance's
    per-host ``host_heartbeats`` map (the liveness record peers and
    ``pio status`` read; ``supervisor.check_peer_liveness`` turns a
    stale entry into a transient ``HostLostError``).
    """
    ctx = ctx or Context(mode="Train", batch=batch)
    meta = Storage.get_metadata()
    if reap_stale_after_s and reap_stale_after_s > 0:
        reap_orphans(meta, stale_after_s=reap_stale_after_s)
    instance = EngineInstance(
        status="INIT",
        start_time=_now(),
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        engine_factory=engine_factory,
        batch=batch,
        env=env or {},
        data_source_params=_params_field(engine_params.data_source_params),
        preparator_params=_params_field(engine_params.preparator_params),
        algorithms_params=_algo_params_field(engine_params.algorithm_params_list),
        serving_params=_params_field(engine_params.serving_params),
    )
    instance_id = meta.engine_instance_insert(instance)
    log.info("EngineInstance %s created; training starts", instance_id)
    # fresh convergence channel per run: attempt summaries from a
    # previous run in this process must not ride this instance's record
    TRAINING.reset_source("train")

    def _stamp(status: str, **extra) -> EngineInstance:
        """Final status flip over the FRESHEST record, so the
        heartbeat's last_heartbeat/attempt stamps survive. ``extra``
        fields (e.g. the phase-time breakdown) ride the same write."""
        cur = meta.engine_instance_get(instance_id) or dataclasses.replace(
            instance, id=instance_id)
        done = dataclasses.replace(cur, status=status, end_time=_now(), **extra)
        meta.engine_instance_update(done)
        return done

    def _on_heartbeat(iso: str, attempt: int) -> None:
        cur = meta.engine_instance_get(instance_id)
        if cur is not None and cur.status == "INIT":  # never clobber a final status
            extra = {}
            if num_processes > 1:
                try:
                    beats = json.loads(cur.host_heartbeats or "{}")
                except ValueError:
                    beats = {}
                beats[str(process_id)] = {"ts": iso, "attempt": attempt}
                extra["host_heartbeats"] = json.dumps(beats)
            meta.engine_instance_update(dataclasses.replace(
                cur, last_heartbeat=iso, attempt=attempt, **extra))

    def _body() -> tuple[int, int]:
        from .tracing import maybe_profile, phase_report, reset_phases

        # each supervised attempt re-runs every phase; without the reset
        # a retried run's persisted breakdown would double-count
        reset_phases(ctx)
        with maybe_profile(getattr(ctx, "profile_dir", None)):
            result = engine.train(ctx, engine_params)
        log.info("training phases: %s", phase_report(ctx))
        models = _persistable(result, instance_id)
        blob = serialize_models(models)
        FAULTS.fire("train.persist")
        Storage.get_models().insert(Model(
            id=instance_id, models=blob,
            checksum=Model.compute_checksum(blob)))
        return len(models), len(blob)

    supervisor = TrainSupervisor(
        max_retries=max_retries,
        retry_backoff_s=retry_backoff_s,
        train_budget_s=train_budget_s,
        heartbeat_s=heartbeat_s,
        on_heartbeat=_on_heartbeat,
    )
    try:
        n_models, n_bytes = supervisor.run(_body)
        from .tracing import phase_times_json

        TRAINING.finish("train", "COMPLETED")
        _stamp("COMPLETED", phase_times=phase_times_json(ctx),
               convergence=json.dumps(TRAINING.summaries("train")))
        log.info("Training completed: instance %s (%d model(s), %d bytes, "
                 "%d attempt(s))",
                 instance_id, n_models, n_bytes, supervisor.attempts)
    except BaseException:
        # BaseException, not Exception: Ctrl-C / SystemExit must not
        # leave the instance stuck at INIT forever
        _stamp("ABORTED")
        log.error("Training aborted:\n%s", traceback.format_exc())
        raise
    return instance_id


def run_evaluation(
    evaluation: Evaluation,
    engine_params_list: Sequence[EngineParams],
    ctx: Context | None = None,
    *,
    evaluation_class: str = "",
    generator_class: str = "",
    batch: str = "",
    best_json_path: str | None = None,
    engine_instance_id: str | None = None,
) -> tuple[str, MetricEvaluatorResult]:
    """Batch-eval a params grid and rank it (CoreWorkflow.runEvaluation,
    CoreWorkflow.scala:96-150 + EvaluationWorkflow.scala:29-41).

    ``engine_instance_id`` additionally stamps the ranked result onto
    that EngineInstance record (ISSUE 15 satellite: eval results used to
    be stdout + EvaluationInstance only, invisible to ``pio status``'s
    completed-runs view). The stamp re-reads the freshest record so it
    composes with concurrent heartbeat/status writers."""
    ctx = ctx or Context(mode="Evaluation", batch=batch)
    meta = Storage.get_metadata()
    instance = EvaluationInstance(
        status="INIT",
        start_time=_now(),
        evaluation_class=evaluation_class,
        engine_params_generator_class=generator_class,
        batch=batch,
    )
    instance_id = meta.evaluation_instance_insert(instance)
    instance = dataclasses.replace(instance, id=instance_id)
    try:
        engine = evaluation.engine
        results = engine.batch_eval(ctx, engine_params_list)
        metrics = evaluation.all_metrics
        evaluator = MetricEvaluator(
            metric=metrics[0], other_metrics=metrics[1:],
            best_json_path=best_json_path,
        )
        result = evaluator.evaluate(ctx, results)
        meta.evaluation_instance_update(
            dataclasses.replace(
                instance,
                status="EVALCOMPLETED",
                end_time=_now(),
                evaluator_results=result.to_one_liner(),
                evaluator_results_html=result.to_html(),
                evaluator_results_json=result.to_json(),
            )
        )
        if engine_instance_id:
            stamp_evaluator_results(engine_instance_id, result,
                                    evaluator_class=evaluation_class)
        log.info("Evaluation completed: instance %s", instance_id)
        return instance_id, result
    except BaseException:
        # as in run_train: Ctrl-C must not strand the record at INIT
        meta.evaluation_instance_update(
            dataclasses.replace(instance, status="ABORTED", end_time=_now())
        )
        raise


def stamp_evaluator_results(engine_instance_id: str,
                            result: MetricEvaluatorResult, *,
                            evaluator_class: str = "",
                            tuning_json: str | None = None) -> None:
    """Stamp a ranked eval result (and optionally a tuning leaderboard)
    onto an EngineInstance so `pio status` can show WHY this model was
    chosen. Re-reads the freshest record before replacing fields —
    heartbeats or a concurrent status flip must not be clobbered. A
    missing instance is a no-op (the eval itself already succeeded)."""
    meta = Storage.get_metadata()
    cur = meta.engine_instance_get(engine_instance_id)
    if cur is None:
        log.warning("stamp_evaluator_results: no engine instance %s",
                    engine_instance_id)
        return
    extra: dict[str, Any] = {}
    if evaluator_class:
        extra["evaluator_class"] = evaluator_class
    if tuning_json is not None:
        extra["tuning"] = tuning_json
    meta.engine_instance_update(dataclasses.replace(
        cur,
        evaluator_results=result.to_one_liner(),
        evaluator_results_json=result.to_json(),
        **extra,
    ))


def prepare_deploy(
    engine: Engine, instance: EngineInstance, ctx: Context | None = None,
    *, engine_dir=None,
) -> TrainResult:
    """Rehydrate models for serving (Engine.prepareDeploy, Engine.scala:
    174-243): deserialize stored models; PersistentModelManifest -> call
    the class's ``load``; RetrainMarker -> retrain from the stored params.

    ``engine_dir`` lets classes referenced by the blob or a manifest be
    re-resolved from the deploying engine's directory, so blobs survive a
    moved/renamed project or a different host (the reference re-resolves
    via its registered jar classpath, CreateServer.scala:61-75)."""
    ctx = ctx or Context(mode="Serving")
    engine_params = engine_params_from_instance(engine, instance)
    names, algos = engine.make_algorithms(engine_params)
    serving = engine.make_serving(engine_params)

    # chaos site: a poisoned/unreachable blob pull (ISSUE 17). Fires
    # before the fetch so a fallback-mode deploy quarantines this
    # instance exactly like a corrupt checksum would.
    FAULTS.fire("replica.blob_pull")
    blob = Storage.get_models().get(instance.id)
    if blob is None:
        raise RuntimeError(f"no model blob for engine instance {instance.id}")
    if blob.checksum:  # pre-integrity blobs have no checksum to check
        actual = Model.compute_checksum(blob.models)
        if actual != blob.checksum:
            raise ModelIntegrityError(
                f"model blob for engine instance {instance.id} is corrupt: "
                f"stored checksum {blob.checksum} != computed {actual}")
    stored = deserialize_models(blob.models, engine_dir=engine_dir)

    models: list[Any] = []
    needs_retrain = any(isinstance(m, RetrainMarker) for m in stored)
    retrained: TrainResult | None = None
    if needs_retrain:
        log.info("Some models are not serializable; retraining at deploy "
                 "(reference Engine.scala:186-208 path)")
        retrained = engine.train(ctx, engine_params)
    for i, (m, algo) in enumerate(zip(stored, algos)):
        if isinstance(m, PersistentModelManifest):
            mod = None
            if engine_dir is not None:  # engine-dir module, scoped import
                mod = _import_engine_scoped(engine_dir, m.module)
            if mod is None:
                # a library module, or (legacy/scoped) already registered
                mod = sys.modules.get(m.module) or importlib.import_module(m.module)
            cls = getattr(mod, m.class_name)
            models.append(cls.load(instance.id, algo.params, ctx))
        elif isinstance(m, RetrainMarker):
            assert retrained is not None
            models.append(retrained.models[i])
        else:
            models.append(m)
    return TrainResult(models=models, algorithms=algos, serving=serving,
                       algorithm_names=names)


def engine_params_from_instance(engine: Engine, instance: EngineInstance) -> EngineParams:
    """Rebuild EngineParams from the instance's stored JSON fields
    (Engine.engineInstanceToEngineParams, Engine.scala:387-440)."""
    def one(js: str, classes) -> tuple[str, Any]:
        d = json.loads(js) if js else {"name": "", "params": {}}
        name = d.get("name", "")
        cls = engine._pick(classes, name, "component")
        pcls = getattr(cls, "params_class", None)
        raw = d.get("params", {})
        from ..controller.params import parse_params

        return (name, parse_params(pcls, raw) if pcls is not None else (raw or None))

    algo_pairs = []
    for d in json.loads(instance.algorithms_params or "[]"):
        name = d.get("name", "")
        cls = engine._pick(engine.algorithm_classes, name, "algorithm")
        pcls = getattr(cls, "params_class", None)
        raw = d.get("params", {})
        from ..controller.params import parse_params

        algo_pairs.append((name, parse_params(pcls, raw) if pcls is not None else (raw or None)))

    return EngineParams(
        data_source_params=one(instance.data_source_params, engine.data_source_classes),
        preparator_params=one(instance.preparator_params, engine.preparator_classes),
        algorithm_params_list=tuple(algo_pairs),
        serving_params=one(instance.serving_params, engine.serving_classes),
    )
