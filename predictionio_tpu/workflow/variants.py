"""Multi-variant serving: N engine variants on one device pool.

The reference PredictionIO deployed many engine variants per server
(engine variants + channels fed the dashboard's A/B view); our engine
server hosted exactly one engine per process. This module closes that
gap: a :class:`VariantTable` registers N fully-deployed engine variants
inside ONE engine-server process — one aiohttp app, one device pool,
one process-wide ExecutableCache — and routes each query to a variant
by a **deterministic hash of the query's entity id** into the
configured traffic weights.

Routing is *weighted rendezvous hashing* (highest-random-weight): per
(variant, key) pair we draw a uniform ``u`` from a keyed blake2b digest
and score the variant ``-weight / ln(u)``; the highest score wins.
Properties that matter for experimentation:

- **Deterministic & stateless** — the same key and the same weights
  always land on the same variant, across processes and restarts, so a
  user's experience is sticky between weight changes and a
  weight-preserving reload re-buckets nobody.
- **Proportional** — the win probability of a variant is exactly its
  weight share (the rendezvous construction, Thaler & Ravishankar).
- **Minimal disruption** — changing one variant's weight only moves
  keys between that variant and the others; keys whose winner did not
  change keep their assignment (consistent-hashing property).

Each variant is a full ``EngineServer`` (its own microbatcher,
AdmissionController plane, SLO tracker, delta patch table, provenance
cache) registered under a lifecycle state ``candidate → live →
retired``. Device-side state is the part deliberately NOT per-variant:
every variant's retrievers share the process ExecutableCache, so N
same-shaped variants compile their top-k/ANN programs once.
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterable

from ..obs.metrics import METRICS

__all__ = [
    "VARIANT_HEADER",
    "VARIANT_STATES",
    "entity_key",
    "bucket_for",
    "VariantEntry",
    "VariantTable",
]

#: Forced-routing override header: bypasses the hash and pins the
#: request to the named variant (capture/replay, debugging, smoke
#: tests). Unknown names 400 rather than falling through to the hash —
#: a replay that silently lands on the wrong variant is worse than one
#: that fails loudly.
VARIANT_HEADER = "X-PIO-Variant"

#: Lifecycle states, in promotion order.
VARIANT_STATES: tuple[str, ...] = ("candidate", "live", "retired")

_STATE_LEVELS = {"candidate": 0, "live": 1, "retired": 2}

#: Query fields probed (in order) for the sticky-routing entity id.
_ENTITY_FIELDS: tuple[str, ...] = (
    "user", "userId", "user_id", "uid", "entityId", "id")

_M_ROUTED = METRICS.counter(
    "pio_serve_routed_total",
    "queries routed to a variant, by mechanism "
    "(hashed / forced header / single-variant default)",
    labelnames=("variant", "how"))
_M_VQUERIES = METRICS.counter(
    "pio_serve_queries_total",
    "per-variant query outcomes (same status vocabulary as "
    "pio_queries_total)",
    labelnames=("variant", "status"))
_M_WEIGHT = METRICS.gauge(
    "pio_variant_weight",
    "configured traffic weight per variant (normalized share is "
    "weight / sum over non-retired variants)",
    labelnames=("variant",))
_M_STATE = METRICS.gauge(
    "pio_variant_state",
    "variant lifecycle: 0 candidate, 1 live, 2 retired",
    labelnames=("variant",))
_M_DELTA_REJECTED = METRICS.counter(
    "pio_variant_delta_rejected_total",
    "delta patches rejected at /reload/delta because the stamped "
    "variant is unknown or retired",
    labelnames=("variant", "reason"))


def entity_key(query: Any) -> str:
    """Stable routing key for a query dict.

    Prefers the first present entity-id field (``user``, ``userId``,
    …); a query with no entity id hashes its canonical JSON so the
    *same* query is still sticky even when anonymous.
    """
    if isinstance(query, dict):
        for f in _ENTITY_FIELDS:
            v = query.get(f)
            if isinstance(v, (str, int)) and not isinstance(v, bool):
                return str(v)
    import json

    try:
        return json.dumps(query, sort_keys=True, separators=(",", ":"),
                          default=str)
    except (TypeError, ValueError):
        return repr(query)


def _uniform(vid: str, key: str) -> float:
    """Keyed uniform draw in (0, 1] for one (variant, key) pair."""
    h = hashlib.blake2b(f"{vid}\x00{key}".encode("utf-8", "replace"),
                        digest_size=8).digest()
    return (int.from_bytes(h, "big") + 1) / (2**64 + 1)


def bucket_for(key: str, weights: dict[str, float]) -> str:
    """Weighted rendezvous hash: pick one variant id for ``key``.

    Variants with weight <= 0 never win. Raises ``ValueError`` when no
    variant has positive weight — the table guarantees this cannot
    happen for a live table (the live variant always has weight > 0 or
    is the only entry).
    """
    best_vid: str | None = None
    best_score = -math.inf
    for vid in sorted(weights):
        w = weights[vid]
        if w <= 0.0:
            continue
        u = _uniform(vid, key)
        # u == 1.0 is a 1-in-2^64 draw; -w/ln(1) would divide by zero.
        score = math.inf if u >= 1.0 else -w / math.log(u)
        if score > best_score:
            best_score = score
            best_vid = vid
    if best_vid is None:
        raise ValueError("no variant with positive weight")
    return best_vid


@dataclass
class VariantEntry:
    """One registered variant: a full EngineServer plus routing state."""

    variant_id: str
    server: Any  # EngineServer; Any avoids a circular import
    state: str = "candidate"
    weight: float = 0.0
    registered_at: float = field(default_factory=time.time)

    def snapshot(self) -> dict:
        return {
            "variantId": self.variant_id,
            "state": self.state,
            "weight": self.weight,
            "registeredAt": self.registered_at,
            "engineInstanceId": getattr(
                self.server, "engine_instance_id", None),
        }


class VariantTable:
    """Registry + router for the variants hosted by one server process.

    Thread-safe: routing runs on the event loop while lifecycle
    operations (register/weight/promote/retire) arrive from management
    endpoints, possibly via ``asyncio.to_thread``. Routing reads take a
    consistent snapshot of ``(weights, entries)`` under the lock and
    hash outside it.
    """

    def __init__(self, default_vid: str, primary_server: Any):
        self._lock = threading.Lock()
        self._entries: dict[str, VariantEntry] = {}
        e = VariantEntry(default_vid, primary_server,
                         state="live", weight=1.0)
        self._entries[default_vid] = e
        self._publish_gauges_locked()

    # -- lifecycle ---------------------------------------------------
    def register(self, vid: str, server: Any, *,
                 weight: float = 0.0) -> VariantEntry:
        if not vid:
            raise ValueError("variant id must be non-empty")
        weight = float(weight)
        if weight < 0.0 or not math.isfinite(weight):
            raise ValueError(f"weight must be finite and >= 0, got {weight}")
        with self._lock:
            if vid in self._entries:
                raise ValueError(f"variant {vid!r} already registered")
            e = VariantEntry(vid, server, state="candidate", weight=weight)
            self._entries[vid] = e
            self._publish_gauges_locked()
            return e

    def set_weight(self, vid: str, weight: float) -> VariantEntry:
        weight = float(weight)
        if weight < 0.0 or not math.isfinite(weight):
            raise ValueError(f"weight must be finite and >= 0, got {weight}")
        with self._lock:
            e = self._require_locked(vid)
            if e.state == "retired":
                raise ValueError(f"variant {vid!r} is retired")
            if e.state == "live" and weight == 0.0 and len(
                    [x for x in self._entries.values()
                     if x.state != "retired"]) > 1:
                # A weightless live variant would strand sticky users
                # only reachable via the forced header; shift traffic
                # with promote() instead.
                raise ValueError(
                    "cannot zero the live variant's weight; "
                    "promote another variant instead")
            e.weight = weight
            self._publish_gauges_locked()
            return e

    def promote(self, vid: str) -> dict:
        """Make ``vid`` the live variant, swapping weights with the
        previous live one. Weight-swap (not weight-zero) keeps the
        total hash mass identical, so ONLY keys belonging to the two
        swapped variants move — everyone else keeps their assignment.
        """
        with self._lock:
            e = self._require_locked(vid)
            if e.state == "retired":
                raise ValueError(f"variant {vid!r} is retired")
            prev = self._live_locked()
            if prev is not None and prev.variant_id == vid:
                return {"promoted": vid, "previousLive": vid}
            if prev is not None:
                prev.state = "candidate"
                prev.weight, e.weight = e.weight, prev.weight
            e.state = "live"
            if e.weight <= 0.0 and all(
                    x.weight <= 0.0 for x in self._entries.values()
                    if x.state != "retired"):
                e.weight = 1.0  # never leave the table unroutable
            self._publish_gauges_locked()
            return {"promoted": vid,
                    "previousLive": prev.variant_id if prev else None}

    def retire(self, vid: str) -> VariantEntry:
        with self._lock:
            e = self._require_locked(vid)
            if e.state == "live":
                raise ValueError(
                    f"variant {vid!r} is live; promote a replacement first")
            e.state = "retired"
            e.weight = 0.0
            self._publish_gauges_locked()
            return e

    # -- routing -----------------------------------------------------
    def route(self, key: str, forced: str | None = None
              ) -> tuple[VariantEntry, str]:
        """Pick the serving variant for a routing key.

        Returns ``(entry, how)`` with ``how`` in ``forced`` / ``hashed``
        / ``default``. A forced name must exist (KeyError otherwise) but
        MAY be retired — capture/replay needs to re-hit a variant after
        the experiment ended. Hashed traffic only ever reaches
        non-retired variants with positive weight.
        """
        with self._lock:
            if forced is not None:
                e = self._entries.get(forced)
                if e is None:
                    raise KeyError(forced)
                _M_ROUTED.inc(variant=forced, how="forced")
                return e, "forced"
            weights = {v.variant_id: v.weight
                       for v in self._entries.values()
                       if v.state != "retired" and v.weight > 0.0}
            if len(weights) <= 1:
                e = (self._entries[next(iter(weights))] if weights
                     else self._live_locked() or
                     next(iter(self._entries.values())))
                _M_ROUTED.inc(variant=e.variant_id, how="default")
                return e, "default"
            entries = dict(self._entries)
        vid = bucket_for(key, weights)
        _M_ROUTED.inc(variant=vid, how="hashed")
        return entries[vid], "hashed"

    def count_query(self, vid: str, status: str) -> None:
        _M_VQUERIES.inc(variant=vid, status=status)

    def count_delta_rejected(self, vid: str, reason: str) -> None:
        _M_DELTA_REJECTED.inc(variant=str(vid), reason=reason)

    # -- introspection -----------------------------------------------
    def get(self, vid: str) -> VariantEntry | None:
        with self._lock:
            return self._entries.get(vid)

    def entries(self) -> list[VariantEntry]:
        with self._lock:
            return list(self._entries.values())

    def servers(self) -> list[Any]:
        with self._lock:
            return [e.server for e in self._entries.values()]

    def live(self) -> VariantEntry | None:
        with self._lock:
            return self._live_locked()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def weights(self) -> dict[str, float]:
        """Routable weight map (non-retired, weight > 0)."""
        with self._lock:
            return {v.variant_id: v.weight
                    for v in self._entries.values()
                    if v.state != "retired" and v.weight > 0.0}

    def snapshot(self) -> dict:
        with self._lock:
            entries = [e.snapshot() for e in self._entries.values()]
            total = sum(e["weight"] for e in entries
                        if e["state"] != "retired" and e["weight"] > 0.0)
        for e in entries:
            share = (e["weight"] / total
                     if total > 0.0 and e["state"] != "retired" else 0.0)
            e["trafficShare"] = share
            e["routed"] = {
                how: int(_M_ROUTED.value(e["variantId"], how))
                for how in ("hashed", "forced", "default")}
        return {"count": len(entries), "variants": entries}

    # -- internals ---------------------------------------------------
    def _require_locked(self, vid: str) -> VariantEntry:
        e = self._entries.get(vid)
        if e is None:
            raise KeyError(vid)
        return e

    def _live_locked(self) -> VariantEntry | None:
        for e in self._entries.values():
            if e.state == "live":
                return e
        return None

    def _publish_gauges_locked(self) -> None:
        for e in self._entries.values():
            _M_WEIGHT.set(e.weight, variant=e.variant_id)
            _M_STATE.set(_STATE_LEVELS[e.state], variant=e.variant_id)


def minimal_disruption(keys: Iterable[str], before: dict[str, float],
                       after: dict[str, float]) -> dict:
    """Diagnostic helper: classify how ``keys`` move between two weight
    maps. Used by tests and the runbook to demonstrate the
    consistent-hashing property; not on the serving path."""
    moved: dict[tuple[str, str], int] = {}
    total = 0
    for k in keys:
        total += 1
        a, b = bucket_for(k, before), bucket_for(k, after)
        if a != b:
            moved[(a, b)] = moved.get((a, b), 0) + 1
    return {"total": total,
            "moved": sum(moved.values()),
            "transitions": {f"{a}->{b}": n for (a, b), n in moved.items()}}
