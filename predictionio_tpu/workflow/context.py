"""Workflow context — the SparkContext analog.

The reference threads a ``SparkContext`` through every DASE method
(reference: core/src/main/scala/io/prediction/core/BaseDataSource.scala:76,
workflow/WorkflowContext.scala:25-44). The TPU runtime's ambient state is a
``jax.sharding.Mesh`` + rng seed + workflow knobs; components receive this
``Context`` as their first work-method argument.

The mesh is constructed lazily from the available devices: a 1-D
``("data",)`` mesh by default (pure data parallel), or the axis spec given
in ``mesh_shape``/``mesh_axes`` (e.g. ``(4, 2), ("data", "model")``).
Under ``jit``-less unit tests this still works — components may ignore the
mesh entirely.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Mapping

log = logging.getLogger("predictionio_tpu.workflow")

__all__ = ["Context", "WorkflowParams"]


@dataclasses.dataclass
class WorkflowParams:
    """(reference: workflow/WorkflowParams.scala)"""

    batch: str = ""
    verbose: int = 2
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    #: backend tuning (the reference's sparkEnv); e.g. donate_buffers, seed
    backend_env: dict = dataclasses.field(default_factory=dict)


class Context:
    """Ambient run state: device mesh, rng seed, app binding, knobs."""

    def __init__(
        self,
        mode: str = "",
        batch: str = "",
        workflow_params: WorkflowParams | None = None,
        mesh_shape: tuple[int, ...] | None = None,
        mesh_axes: tuple[str, ...] | None = None,
        seed: int = 0,
        app_name: str | None = None,
        channel_name: str | None = None,
        extra: Mapping[str, Any] | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 0,
        profile_dir: str | None = None,
        process_id: int = 0,
        num_processes: int = 1,
    ):
        self.mode = mode
        self.batch = batch
        self.workflow_params = workflow_params or WorkflowParams()
        self.seed = seed
        self.app_name = app_name
        self.channel_name = channel_name
        self.extra = dict(extra or {})
        #: mid-training checkpoint/resume knobs (workflow/checkpoint.py);
        #: algorithms that support step-level resume read these
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        #: jax.profiler trace output dir for this run (workflow/tracing.py)
        self.profile_dir = profile_dir
        #: elastic multi-host topology (pio train --process-id/
        #: --num-processes); >1 processes switch checkpointing to the
        #: sharded manifest protocol
        self.process_id = process_id
        self.num_processes = num_processes
        #: set by Engine.train around each algorithm's train() call —
        #: namespaces per-algorithm state such as checkpoints
        self.current_algorithm: str | None = None
        self._mesh = None
        self._mesh_shape = mesh_shape
        self._mesh_axes = mesh_axes

    def checkpointer(self, subdir: str = ""):
        """TrainCheckpointer for this run, or None when checkpointing is
        off (no --checkpoint-dir). The path is namespaced by the algorithm
        currently training (Engine.train sets ``current_algorithm``) so
        multiple algorithm entries never clobber each other's steps.

        Multi-process runs (``num_processes > 1``) get a
        ``ShardedTrainCheckpointer`` over the same directory: each
        process writes only its factor shard, process 0 commits the
        manifest, and a later run at ANY process count resumes from it
        (N→M elastic resume)."""
        if not self.checkpoint_dir:
            return None
        from .checkpoint import ShardedTrainCheckpointer, TrainCheckpointer
        from pathlib import Path

        d = Path(self.checkpoint_dir)
        if self.current_algorithm:
            d = d / self.current_algorithm.replace("/", "_")
        d = d / subdir if subdir else d
        if self.num_processes > 1:
            return ShardedTrainCheckpointer(
                d, process_id=self.process_id,
                num_processes=self.num_processes)
        return TrainCheckpointer(d)

    # -- devices -----------------------------------------------------------
    @property
    def mesh(self):
        """Lazily-built jax Mesh (the WorkflowContext.apply analog —
        constructing it is what 'new SparkContext' is to the reference)."""
        if self._mesh is None:
            from ..parallel.mesh import make_mesh

            self._mesh = make_mesh(self._mesh_shape, self._mesh_axes)
        return self._mesh

    def rng(self, salt: int = 0):
        import jax

        return jax.random.PRNGKey(self.seed + salt)

    # -- event store access (PEventStore binding) ---------------------------
    def event_store(self):
        from ..store import EventStore

        return EventStore(default_app_name=self.app_name,
                          default_channel_name=self.channel_name)

    def __repr__(self) -> str:
        return f"Context(mode={self.mode!r}, batch={self.batch!r}, seed={self.seed})"
