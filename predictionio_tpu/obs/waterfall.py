"""Per-request stage waterfalls: where did each millisecond go?

The PR-5 telemetry core answers "how slow is serving overall" with
aggregate histograms; this module answers "where inside ONE request did
the time go". Every serve request carries a :class:`Waterfall` — a
per-request stage timeline keyed by the canonical serving stages:

    admission -> queue_wait -> batch_form -> host_assembly ->
    device_dispatch -> device_compute -> result_scatter -> response_write

The invariant this module is built around: **stage durations sum to the
request's wall latency** (within scheduler noise). ``response_write`` is
computed as the *residual* at :meth:`Waterfall.finish` — wall minus the
sum of the marked stages — so the invariant holds structurally rather
than by hoping every code path remembered to mark.

Attribution mechanics
---------------------

``mark(stage)`` attributes the time elapsed *since the previous mark* to
``stage``, and marks are **additive** — a request served by two models
accumulates two ``device_compute`` slices into one stage total. Deep
code (``ops/retrieval._dispatch_topk``, ``serve_query_batch``) never
threads a waterfall object through its signatures; it calls the
module-level :func:`mark_stage`, which resolves the ambient sink from a
contextvar (copied into ``asyncio.to_thread`` workers, so the fallback
serve path attributes correctly without plumbing).

The batched path is two-phase: the request's own waterfall marks
``admission`` at submit and receives ``queue_wait`` when its batch is
cut; the batch-shared stages (formation, host assembly, device dispatch/
compute, scatter) are accumulated on a per-dispatch :class:`BatchClock`
(installed as the sink inside the dispatch worker thread) and merged
into every member's waterfall when the batch completes. Batch-shared
time is attributed *in full* to each member — a request that waited
through a 3 ms device step experienced all 3 ms of it.

Two attribution caveats, documented rather than hidden:

- Retrievers whose ``invoke`` blocks internally (ShardedDeviceRetriever
  fences inside the shard loop) land their compute in ``device_dispatch``
  rather than ``device_compute``; the ``hostShare``/``deviceShare``
  split counts both as device time, so the split is robust either way.
- Models with no device retriever (host scoring) have no device stages;
  their predict time lands in ``result_scatter`` (everything between
  assembly and response handoff).

Per-stage histograms are separate unlabeled families
(``pio_serve_stage_<stage>_seconds``) per the registry's one-family-per-
site rule, plus ``pio_serve_waterfall_wall_seconds`` for the wall side
of the invariant.
"""

from __future__ import annotations

import threading
import time
from contextvars import ContextVar

from .metrics import METRICS

__all__ = [
    "STAGES",
    "DEVICE_STAGES",
    "STAGE_HISTOGRAMS",
    "Waterfall",
    "BatchClock",
    "mark_stage",
    "set_stage_sink",
    "reset_stage_sink",
    "current_sink",
    "stage_sink_active",
    "stage_summary",
]

#: Canonical stage order of one serve request, ingress to egress.
STAGES: tuple[str, ...] = (
    "admission",        # ingress -> body parsed + admission decided
    "queue_wait",       # submitted to the batcher -> batch cut
    "batch_form",       # batch cut -> dispatch worker running
    "host_assembly",    # id->row decode, padding, batch matrix build
    "device_dispatch",  # the invoke() call itself (enqueue to XLA)
    "device_compute",   # block_until_ready delta around the invoke
    "result_scatter",   # unpad, host pull, blend, fan-out to futures
    "response_write",   # residual: future resolution -> bytes on wire
)

#: Stages counted as device time in the hostShare/deviceShare split.
DEVICE_STAGES: tuple[str, ...] = ("device_dispatch", "device_compute")

STAGE_HISTOGRAMS = {
    s: METRICS.histogram(
        f"pio_serve_stage_{s}_seconds",
        f"per-request time attributed to the {s} serving stage")
    for s in STAGES
}

_H_WALL = METRICS.histogram(
    "pio_serve_waterfall_wall_seconds",
    "wall latency of requests carrying a stage waterfall (the sum-to-wall"
    " invariant's right-hand side)")


class _Clock:
    """Shared cursor mechanics: ``mark(stage)`` attributes time since the
    previous mark, additively per stage."""

    __slots__ = ("t0", "_last", "stages", "_order")

    def __init__(self, now: float | None = None):
        now = time.perf_counter() if now is None else now
        self.t0 = now
        self._last = now
        self.stages: dict[str, float] = {}
        self._order: list[str] = []

    def mark(self, stage: str, now: float | None = None) -> None:
        now = time.perf_counter() if now is None else now
        dt = now - self._last
        if dt < 0.0:
            dt = 0.0
        if stage not in self.stages:
            self._order.append(stage)
        self.stages[stage] = self.stages.get(stage, 0.0) + dt
        self._last = now

    def add(self, stage: str, dt: float) -> None:
        """Attribute an externally measured duration without moving the
        cursor (used to merge batch-shared stage time into members)."""
        if dt <= 0.0:
            return
        if stage not in self.stages:
            self._order.append(stage)
        self.stages[stage] = self.stages.get(stage, 0.0) + dt

    def cursor(self, now: float | None = None) -> None:
        """Re-seat the cursor so the next ``mark`` doesn't inherit
        unrelated elapsed time (bench loops re-seat per iteration)."""
        self._last = time.perf_counter() if now is None else now


class Waterfall(_Clock):
    """One request's stage timeline, finished exactly once."""

    __slots__ = ("rid", "path", "wall", "status", "stalled_stage",
                 "meta", "finished")

    def __init__(self, rid: str | None = None, path: str = "serve"):
        super().__init__()
        self.rid = rid
        self.path = path
        self.wall: float | None = None
        self.status: str | None = None
        self.stalled_stage: str | None = None
        self.meta: dict = {}
        self.finished = False

    def merge_batch(self, clock: "BatchClock") -> None:
        # list() snapshot: a watchdog-abandoned zombie thread may still
        # be marking stages on this clock while the loop merges it
        for stage, dt in list(clock.stages.items()):
            self.add(stage, dt)

    def finish(self, status: str | None = None,
               record: bool = True) -> "Waterfall":
        """Close the waterfall: wall = now - ingress; the unattributed
        residual becomes ``response_write`` so stages sum to wall by
        construction. Records the per-stage histograms unless told not
        to. Idempotent — the first finish wins."""
        if self.finished:
            return self
        self.finished = True
        self.wall = time.perf_counter() - self.t0
        self.status = status
        residual = self.wall - sum(self.stages.values())
        if residual > 0.0:
            self.add("response_write", residual)
        if record:
            for stage, dt in self.stages.items():
                h = STAGE_HISTOGRAMS.get(stage)
                if h is not None:
                    h.record(dt)
            _H_WALL.record(self.wall)
        return self

    def to_dict(self) -> dict:
        wall = self.wall if self.wall is not None else (
            time.perf_counter() - self.t0)
        d: dict = {
            "requestId": self.rid,
            "path": self.path,
            "status": self.status,
            "finished": self.finished,
            "wallMs": round(wall * 1e3, 3),
            "stagesMs": {s: round(self.stages[s] * 1e3, 3)
                         for s in STAGES if s in self.stages},
        }
        if self.stalled_stage is not None:
            d["stalledStage"] = self.stalled_stage
        if self.meta:
            d["context"] = dict(self.meta)
        return d


class BatchClock(_Clock):
    """Stage accumulator for ONE micro-batch dispatch, installed as the
    stage sink inside the dispatch worker thread and merged into every
    member waterfall on completion."""

    __slots__ = ()

    def in_progress(self) -> str:
        """The stage underway right now — the canonical successor of the
        last completed mark. This is what the watchdog stamps onto hung
        requests as ``stalledStage``: a dispatch that never marked
        anything stalled before batch formation completed."""
        if not self._order:
            return "batch_form"
        last = self._order[-1]
        try:
            i = STAGES.index(last)
        except ValueError:
            return last
        return STAGES[i + 1] if i + 1 < len(STAGES) else last


# ---------------------------------------------------------------------------
# Ambient sink: deep code marks stages without signature plumbing.

_SINK: ContextVar[_Clock | None] = ContextVar("pio_stage_sink", default=None)


def set_stage_sink(sink: _Clock | None):
    """Install ``sink`` as the ambient stage sink for this context;
    returns the reset token."""
    return _SINK.set(sink)


def reset_stage_sink(token) -> None:
    _SINK.reset(token)


def current_sink() -> _Clock | None:
    return _SINK.get()


def stage_sink_active() -> bool:
    return _SINK.get() is not None


def mark_stage(stage: str) -> None:
    """Attribute time-since-last-mark to ``stage`` on the ambient sink;
    a no-op (one contextvar read) when no request is being attributed —
    training and bench paths pay nothing."""
    sink = _SINK.get()
    if sink is not None:
        sink.mark(stage)


# ---------------------------------------------------------------------------
# Aggregate views.

_split_lock = threading.Lock()


def stage_summary() -> dict:
    """JSON-ready aggregate of the stage histograms plus the
    ``hostShare``/``deviceShare`` split (shares of total attributed
    time; device = dispatch + compute, see module docstring)."""
    stages = {}
    total = 0.0
    device = 0.0
    for s in STAGES:
        snap = STAGE_HISTOGRAMS[s].snapshot()
        stages[s] = snap
        total += snap["sum"]
        if s in DEVICE_STAGES:
            device += snap["sum"]
    wall = _H_WALL.snapshot()
    host = total - device
    return {
        "stages": stages,
        "wall": wall,
        "hostShare": round(host / total, 4) if total > 0 else None,
        "deviceShare": round(device / total, 4) if total > 0 else None,
    }
