"""Unified telemetry core (ISSUE 5).

The reference leaned on Spark's UI as its implicit profiler; this package
replaces that substrate with a process-wide metrics registry
(:mod:`.metrics`: counters, gauges, log-bucketed latency histograms with
p50/p95/p99 snapshots and Prometheus text exposition) and request-scoped
tracing (:mod:`.trace`: an ``X-PIO-Request-ID`` propagated from ingress
through the journal/drainer on the event path and through the
micro-batcher/feedback loop on the query path, emitted as structured
JSON log lines joinable by trace id).

Every subsystem instruments through the module-global ``METRICS``
registry; the per-subsystem ``stats()`` dicts keep their JSON shapes and
the servers additionally expose ``GET /metrics`` for scrapers.
"""

from .metrics import METRICS, MetricsRegistry  # noqa: F401
from .trace import (  # noqa: F401
    TRACE_HEADER,
    current_request_id,
    ensure_request_id,
    new_request_id,
    span,
    trace_event,
)

__all__ = [
    "METRICS", "MetricsRegistry", "TRACE_HEADER", "current_request_id",
    "ensure_request_id", "new_request_id", "span", "trace_event",
]
