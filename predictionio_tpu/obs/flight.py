"""Always-on flight recorder: the last N request waterfalls, dumped on
incident.

A bounded ring buffer holds the most recent finished (or hung) request
waterfalls together with ambient server context (mode, queue depth —
whatever the registered context provider reports). When something goes
wrong — the dispatch watchdog fires, the mode ladder enters brownout or
degraded, or deadline expiries burst — the ring is dumped to a JSON
incident file *at that moment*, capturing the requests that led into the
incident rather than the ones that came after someone noticed.

The ring is always on: recording one request is a dict build plus a
deque append under a lock, no I/O. Dumps are rate-limited per reason
(``cooldown_s``) so a flapping mode ladder cannot fill a disk.

On-demand access: ``GET /debug/flight.json`` on the engine server and
``pio admin flight`` both return :meth:`FlightRecorder.snapshot`.

Like ``METRICS`` and ``FAULTS``, the process-wide singleton ``FLIGHT``
is the one instance everything records into; tests reset it between
cases via :meth:`reset`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .metrics import METRICS

__all__ = ["FlightRecorder", "FLIGHT"]

_C_DUMPS = METRICS.counter(
    "pio_flight_dumps_total",
    "flight-recorder incident dumps written, by trigger reason",
    labelnames=("reason",))
_C_SUPPRESSED = METRICS.counter(
    "pio_flight_dumps_suppressed_total",
    "incident dumps suppressed by the per-reason cooldown",
    labelnames=("reason",))
_G_RECORDS = METRICS.gauge(
    "pio_flight_records",
    "request waterfalls currently held in the flight-recorder ring")


def _default_dump_dir() -> str:
    return (os.environ.get("PIO_FLIGHT_DIR")
            or os.path.join(os.path.expanduser("~"), ".pio_tpu", "flight"))


class FlightRecorder:
    """Bounded ring of request-waterfall records + incident dumping."""

    def __init__(self, capacity: int = 256, dump_dir: str | None = None,
                 cooldown_s: float = 30.0, burst_threshold: int = 10,
                 burst_window_s: float = 5.0):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self.dump_dir = dump_dir
        self.cooldown_s = cooldown_s
        self.burst_threshold = burst_threshold
        self.burst_window_s = burst_window_s
        self._last_dump: dict[str, float] = {}   # reason -> monotonic
        self._expiries: deque = deque(maxlen=1024)
        self.last_dump_path: str | None = None
        self.last_dump_reason: str | None = None
        self.last_dump_time: float | None = None  # wall clock, epoch s
        self.dumps = 0
        self._context_fn = None
        self._incident_listeners: list = []

    # -- configuration -----------------------------------------------
    def configure(self, *, capacity: int | None = None,
                  dump_dir: str | None = None,
                  cooldown_s: float | None = None,
                  burst_threshold: int | None = None,
                  burst_window_s: float | None = None) -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, capacity))
            if dump_dir is not None:
                self.dump_dir = dump_dir
            if cooldown_s is not None:
                self.cooldown_s = cooldown_s
            if burst_threshold is not None:
                self.burst_threshold = burst_threshold
            if burst_window_s is not None:
                self.burst_window_s = burst_window_s

    def set_context_provider(self, fn) -> None:
        """``fn() -> dict`` of ambient server context (mode, queue depth,
        inflight); called at record and dump time, exceptions swallowed —
        observability must never take the server down."""
        self._context_fn = fn

    def add_incident_listener(self, fn) -> None:
        """``fn(reason, path)`` runs after every non-suppressed incident
        dump (ISSUE 13: the capture ring flushes its golden traffic the
        moment something goes wrong — the requests that led into the
        incident are exactly the ones worth keeping). Exceptions are
        swallowed; listeners are cleared by :meth:`reset`."""
        self._incident_listeners.append(fn)

    def _context(self) -> dict:
        fn = self._context_fn
        if fn is None:
            return {}
        try:
            return dict(fn())
        except Exception:
            return {}

    # -- recording -----------------------------------------------------
    def record(self, waterfall_dict: dict) -> None:
        """Append one finished request's waterfall to the ring."""
        with self._lock:
            self._ring.append(waterfall_dict)
            _G_RECORDS.set(len(self._ring))

    def note_hung(self, waterfall_dict: dict) -> None:
        """Record a request the watchdog declared hung — pushed *before*
        the incident dump so the dump contains the victim."""
        d = dict(waterfall_dict)
        d["hung"] = True
        self.record(d)

    def note_deadline_expired(self) -> str | None:
        """Count one deadline expiry; when ``burst_threshold`` expiries
        land within ``burst_window_s``, trigger a ``deadline_burst``
        incident. Returns the dump path when one was written."""
        now = time.monotonic()
        with self._lock:
            self._expiries.append(now)
            cutoff = now - self.burst_window_s
            recent = sum(1 for t in self._expiries if t >= cutoff)
        if recent >= self.burst_threshold:
            return self.incident("deadline_burst")
        return None

    # -- dumping -------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            records = list(self._ring)
        return {
            "capacity": self._ring.maxlen,
            "records": records,
            "context": self._context(),
            "lastDump": {
                "path": self.last_dump_path,
                "reason": self.last_dump_reason,
            },
            "dumps": self.dumps,
        }

    def incident(self, reason: str, force: bool = False) -> str | None:
        """Dump the ring to ``<dump_dir>/flight-<reason>-<ts>.json``.
        Returns the path, or None when suppressed by the cooldown."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if not force and last is not None and (
                    now - last) < self.cooldown_s:
                _C_SUPPRESSED.inc(reason=reason)
                return None
            self._last_dump[reason] = now
        payload = self.snapshot()
        payload["reason"] = reason
        payload["wallTime"] = time.time()
        try:
            # ISSUE 12: OOM-adjacent incidents are self-contained — the
            # dump carries the HBM watermark + top executables by bytes.
            # Local import: flight is imported by device's metric deps.
            from .device import LEDGER

            payload["deviceLedger"] = LEDGER.incident_brief()
        except Exception:
            pass  # telemetry-of-telemetry must never block a dump
        dump_dir = self.dump_dir or _default_dump_dir()
        path = os.path.join(
            dump_dir, f"flight-{reason}-{int(time.time() * 1e3)}.json")
        try:
            os.makedirs(dump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2, default=str)
            os.replace(tmp, path)
        except OSError:
            # a full disk must not take serving down — but the incident
            # still happened, so listeners (capture flush) still run
            self._notify_incident(reason, None)
            return None
        with self._lock:
            self.last_dump_path = path
            self.last_dump_reason = reason
            self.last_dump_time = payload["wallTime"]
            self.dumps += 1
        _C_DUMPS.inc(reason=reason)
        self._notify_incident(reason, path)
        return path

    def _notify_incident(self, reason: str, path: str | None) -> None:
        for fn in list(self._incident_listeners):
            try:
                fn(reason, path)
            except Exception:  # noqa: BLE001 — observability never kills
                pass

    # -- views ---------------------------------------------------------
    def stats(self) -> dict:
        """Compact block for /stats.json."""
        with self._lock:
            return {
                "records": len(self._ring),
                "capacity": self._ring.maxlen,
                "dumps": self.dumps,
                "lastDumpReason": self.last_dump_reason,
                "lastDumpPath": self.last_dump_path,
                # wall-clock stamp lets the fleet collector correlate a
                # replica dump with router-side context for the window
                "lastDumpTime": self.last_dump_time,
            }

    def reset(self) -> None:
        """Test isolation: empty the ring and forget dump history (the
        configuration — capacity, dump dir — survives)."""
        with self._lock:
            self._ring.clear()
            self._expiries.clear()
            self._last_dump.clear()
            self.last_dump_path = None
            self.last_dump_reason = None
            self.last_dump_time = None
            self.dumps = 0
            self._incident_listeners.clear()
            _G_RECORDS.set(0)


#: the process-wide recorder every serve path records into
FLIGHT = FlightRecorder()
