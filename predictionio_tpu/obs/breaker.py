"""Circuit-breaker gauges shared by every breaker in the codebase.

Two breakers exist today (the feedback publisher and the ingest
drainer); both report through the same two families so one dashboard
panel covers them: ``pio_breaker_state{subsystem=...}`` (0 closed,
1 half-open, 2 open — alert on ``max_over_time > 0``) and
``pio_breaker_transitions_total{subsystem=...,to=...}`` (a stuck-open
breaker shows a transition count that stopped moving while the state
gauge stays at 2).
"""

from __future__ import annotations

from .metrics import METRICS

__all__ = ["breaker_set", "BREAKER_LEVEL"]

BREAKER_LEVEL = {"closed": 0, "half_open": 1, "open": 2}

_M_STATE = METRICS.gauge(
    "pio_breaker_state",
    "circuit-breaker state by subsystem (0=closed 1=half-open 2=open)",
    labelnames=("subsystem",))
_M_TRANSITIONS = METRICS.counter(
    "pio_breaker_transitions_total",
    "circuit-breaker state transitions by subsystem and target state",
    labelnames=("subsystem", "to"))


def breaker_set(subsystem: str, state: str,
                prev: str | None = None) -> None:
    """Stamp the state gauge; count the transition when ``prev`` (the
    state before this change) differs."""
    _M_STATE.set(BREAKER_LEVEL.get(state, 0), subsystem=subsystem)
    if prev is not None and prev != state:
        _M_TRANSITIONS.inc(subsystem=subsystem, to=state)
