"""SLO burn-rate engine: declared objectives, multi-window burn rates.

An :class:`Objective` declares what "good" means for one dimension of
serving — e.g. *latency*: wall latency under a threshold for at least
99% of requests; *availability*: non-server-error outcomes for at least
99.9%. The :class:`SloTracker` books every request outcome into 5-second
time buckets and reports, per objective and per window (5 m / 1 h), the
**burn rate**: the observed bad fraction divided by the error budget
``1 - target``.

Burn rate reads directly as alert severity (Google SRE workbook
multi-window convention): 1.0 means the error budget is being consumed
exactly at the sustainable rate; 14.4 on the 5 m window means the whole
30-day budget would be gone in ~2 days. The short window catches fast
burns, the long window keeps the alert from flapping.

Exported as labeled gauges (``pio_slo_burn_rate{slo,window}``,
``pio_slo_bad_fraction{slo,window}``) plus an outcome counter, refreshed
at most once per second on the observe path (computing a window sum
walks up to 720 buckets — cheap, but not per-request cheap). The full
:meth:`summary` recomputes fresh and feeds ``/health.json``,
``/stats.json`` and the dashboard.

``now_fn`` is injectable so the synthetic burn test can replay hours of
traffic in milliseconds.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from .metrics import METRICS

__all__ = ["Objective", "SloTracker", "default_objectives",
           "ingest_objectives", "merge_slo_summaries"]

_G_BURN = METRICS.gauge(
    "pio_slo_burn_rate",
    "error-budget burn rate per objective and window (1.0 = budget "
    "consumed exactly at the sustainable rate)",
    labelnames=("slo", "window"))
_G_BAD = METRICS.gauge(
    "pio_slo_bad_fraction",
    "observed bad-event fraction per objective and window",
    labelnames=("slo", "window"))
_C_EVENTS = METRICS.counter(
    "pio_slo_events_total",
    "request outcomes booked against SLO objectives",
    labelnames=("slo", "outcome"))

#: multi-window burn convention: fast window catches, slow window confirms
WINDOWS_S: dict[str, float] = {"5m": 300.0, "1h": 3600.0}

_BUCKET_S = 5.0


@dataclass(frozen=True)
class Objective:
    """One declared objective. ``kind`` is ``"latency"`` (bad when wall
    latency exceeds ``threshold_s``) or ``"availability"`` (bad when the
    request outcome was a server-side failure)."""

    name: str
    kind: str
    target: float                     # e.g. 0.999 -> 0.1% error budget
    threshold_s: float | None = None  # latency objectives only

    def is_bad(self, latency_s: float, ok: bool) -> bool:
        if self.kind == "latency":
            return latency_s > float(self.threshold_s)
        return not ok

    @property
    def budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


def default_objectives(deadline_s: float = 0.25) -> list[Objective]:
    """The serving defaults: p99-style latency under the request
    deadline, availability three nines."""
    return [
        Objective(name="latency", kind="latency", target=0.99,
                  threshold_s=deadline_s),
        Objective(name="availability", kind="availability", target=0.999),
    ]


def ingest_objectives(target: float = 0.999) -> list[Objective]:
    """The event plane's single objective: ingestion availability.
    Latency is deliberately absent — the durable-journal write path is
    bounded by fsync policy, and a latency SLO there would just alias
    the journal metrics that already exist."""
    return [Objective(name="ingest-availability", kind="availability",
                      target=target)]


class SloTracker:
    """Time-bucketed outcome counts + burn-rate computation."""

    def __init__(self, objectives: list[Objective] | None = None,
                 now_fn=time.monotonic):
        self.objectives = list(objectives or default_objectives())
        self._now = now_fn
        self._lock = threading.Lock()
        n_buckets = int(max(WINDOWS_S.values()) / _BUCKET_S) + 2
        # each entry: [bucket_start_s, {objective_name: [good, bad]}]
        self._buckets: deque = deque(maxlen=n_buckets)
        self._last_gauge_refresh = -1e18

    def observe(self, latency_s: float, ok: bool = True) -> None:
        """Book one request outcome against every objective."""
        now = self._now()
        bucket_start = now - (now % _BUCKET_S)
        with self._lock:
            if not self._buckets or self._buckets[-1][0] != bucket_start:
                self._buckets.append(
                    [bucket_start,
                     {o.name: [0, 0] for o in self.objectives}])
            counts = self._buckets[-1][1]
            for o in self.objectives:
                bad = o.is_bad(latency_s, ok)
                slot = counts.setdefault(o.name, [0, 0])
                slot[1 if bad else 0] += 1
                _C_EVENTS.inc(slo=o.name, outcome="bad" if bad else "good")
            refresh = (now - self._last_gauge_refresh) >= 1.0
            if refresh:
                self._last_gauge_refresh = now
        if refresh:
            self.refresh_gauges()

    def _window_counts(self, window_s: float, now: float) -> dict:
        """{objective: (good, bad)} over the trailing window."""
        cutoff = now - window_s
        out = {o.name: [0, 0] for o in self.objectives}
        with self._lock:
            for bucket_start, counts in self._buckets:
                # a bucket counts while any part of it overlaps the window
                if bucket_start + _BUCKET_S <= cutoff:
                    continue
                for name, (good, bad) in counts.items():
                    slot = out.setdefault(name, [0, 0])
                    slot[0] += good
                    slot[1] += bad
        return {k: (v[0], v[1]) for k, v in out.items()}

    def burn_rates(self) -> dict:
        """{objective: {window: burn_rate}} — 0.0 with no traffic."""
        now = self._now()
        out: dict = {}
        for label, window_s in WINDOWS_S.items():
            counts = self._window_counts(window_s, now)
            for o in self.objectives:
                good, bad = counts.get(o.name, (0, 0))
                total = good + bad
                frac = (bad / total) if total else 0.0
                out.setdefault(o.name, {})[label] = frac / o.budget
        return out

    def refresh_gauges(self) -> None:
        now = self._now()
        for label, window_s in WINDOWS_S.items():
            counts = self._window_counts(window_s, now)
            for o in self.objectives:
                good, bad = counts.get(o.name, (0, 0))
                total = good + bad
                frac = (bad / total) if total else 0.0
                _G_BAD.set(frac, slo=o.name, window=label)
                _G_BURN.set(frac / o.budget, slo=o.name, window=label)

    def summary(self) -> dict:
        """JSON block for /health.json, /stats.json and the dashboard.
        ``breaching`` = fast-window burn above 1.0 (budget being eaten
        faster than sustainable)."""
        now = self._now()
        by_window = {label: self._window_counts(window_s, now)
                     for label, window_s in WINDOWS_S.items()}
        objectives = []
        any_breaching = False
        for o in self.objectives:
            windows = {}
            for label in WINDOWS_S:
                good, bad = by_window[label].get(o.name, (0, 0))
                total = good + bad
                frac = (bad / total) if total else 0.0
                windows[label] = {
                    "events": total,
                    # raw integer counts travel with the summary so the
                    # fleet aggregator can merge EXACTLY (summing the
                    # rounded fractions below would compound error)
                    "good": good,
                    "bad": bad,
                    "badFraction": round(frac, 6),
                    "burnRate": round(frac / o.budget, 4),
                }
            breaching = windows["5m"]["burnRate"] > 1.0
            any_breaching = any_breaching or breaching
            entry = {
                "name": o.name,
                "kind": o.kind,
                "target": o.target,
                "windows": windows,
                "breaching": breaching,
            }
            if o.threshold_s is not None:
                entry["thresholdMs"] = round(o.threshold_s * 1e3, 3)
            objectives.append(entry)
        return {"objectives": objectives, "breaching": any_breaching}


def _window_raw(win: dict) -> tuple[int, int]:
    """(good, bad) from one summary window dict. Summaries from this
    version carry raw counts; a version-skewed replica without them is
    reconstructed from events * badFraction (rounded — the best the old
    wire format allows)."""
    events = int(win.get("events", 0))
    if "good" in win and "bad" in win:
        return int(win["good"]), int(win["bad"])
    bad = int(round(events * float(win.get("badFraction", 0.0))))
    return events - bad, bad


def merge_slo_summaries(summaries: list[dict]) -> dict:
    """Fleet-truth SLO: sum the raw good/bad counts of per-replica
    :meth:`SloTracker.summary` blocks per (objective, window) and
    recompute fractions/burn from the totals — the PR-11 burn engine's
    arithmetic re-run over merged buckets, not an average of averages.

    Objectives are keyed by name; target/kind/threshold come from the
    first replica that declares them (the fleet shares one engine
    build, so these agree except during a rolling deploy — where the
    first-seen value is as good as any).
    """
    merged: dict[str, dict] = {}
    order: list[str] = []
    for s in summaries or []:
        for obj in (s or {}).get("objectives", []):
            name = obj.get("name")
            if not name:
                continue
            ent = merged.get(name)
            if ent is None:
                ent = {"name": name, "kind": obj.get("kind"),
                       "target": float(obj.get("target", 0.0)),
                       "windows": {}}
                if obj.get("thresholdMs") is not None:
                    ent["thresholdMs"] = obj["thresholdMs"]
                merged[name] = ent
                order.append(name)
            for label, win in (obj.get("windows") or {}).items():
                good, bad = _window_raw(win)
                slot = ent["windows"].setdefault(label, [0, 0])
                slot[0] += good
                slot[1] += bad
    objectives = []
    any_breaching = False
    for name in order:
        ent = merged[name]
        budget = max(1.0 - ent["target"], 1e-9)
        windows = {}
        for label, (good, bad) in ent["windows"].items():
            total = good + bad
            frac = (bad / total) if total else 0.0
            windows[label] = {
                "events": total,
                "good": good,
                "bad": bad,
                "badFraction": round(frac, 6),
                "burnRate": round(frac / budget, 4),
            }
        breaching = windows.get("5m", {}).get("burnRate", 0.0) > 1.0
        any_breaching = any_breaching or breaching
        out = {"name": name, "kind": ent["kind"], "target": ent["target"],
               "windows": windows, "breaching": breaching}
        if "thresholdMs" in ent:
            out["thresholdMs"] = ent["thresholdMs"]
        objectives.append(out)
    return {"objectives": objectives, "breaching": any_breaching,
            "replicas": len(summaries or [])}
