"""Golden-traffic capture: a sampled, bounded ring of request/response/
provenance triples persisted to an on-disk capture journal.

ISSUE 13: the serving hot path is about to be rebuilt (device-resident
dispatch, multi-engine variants), and "same answers on real traffic" is
the gate every rewrite must pass. This module is the capture half of
that harness: every served query can be recorded — request (the
EFFECTIVE query, post brownout clamp, so replay is deterministic),
response body, HTTP status, latency, and the provenance envelope naming
the exact model/config that produced it (obs/replay.py re-issues and
diffs).

Design:

- **Hot path is a deque append.** ``record()`` samples, builds one dict
  and appends it to a bounded ring under a lock — no serialization, no
  I/O. The bench gate (bench.py capture_overhead_bench) pins this:
  capture on (sample 1.0) must stay within 5% of capture off.
- **Persistence reuses the WAL.** The ring flushes to an
  ``EventJournal`` (storage/journal.py) — the same CRC-framed segment
  format, torn-tail repair and rotation discipline the ingestion WAL
  already proved. Flushes happen when the ring fills (rotation), on
  flight-recorder incidents (the requests that led in are exactly the
  golden traffic worth keeping), on ``pio capture stop``, and at close.
- **Bounded as a disk ring.** The capture journal never backpressures
  serving: on ``JournalFull`` the OLDEST captured segments are released
  (cursor advance + segment GC) to make room — drop-oldest, matching
  the in-memory ring's semantics, instead of the WAL's 503.
- **Readable offline.** ``iter_capture()`` reads a capture directory
  without touching the writer's cursor (``storage/journal.py
  iter_journal_records``) — `pio capture export` and `pio replay`
  consume it.

Counters/gauges ride the PR-5 registry (``pio_capture_*``, catalogued
in docs/operations.md).
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
from collections import deque
from pathlib import Path
from typing import Iterator

from ..storage.journal import EventJournal, JournalFull, iter_journal_records
from .metrics import METRICS

log = logging.getLogger("predictionio_tpu.capture")

__all__ = ["CaptureRing", "iter_capture"]

_M_RECORDS = METRICS.counter(
    "pio_capture_records_total",
    "capture decisions per served request "
    "(captured/sampled_out/dropped)",
    labelnames=("outcome",))
_M_FLUSHES = METRICS.counter(
    "pio_capture_flushes_total",
    "capture-ring flushes to the on-disk journal, by trigger "
    "(ring_full/incident/manual/close)",
    labelnames=("trigger",))
_G_RING = METRICS.gauge(
    "pio_capture_ring_records",
    "records currently buffered in the in-memory capture ring")
_G_ENABLED = METRICS.gauge(
    "pio_capture_enabled",
    "1 while golden-traffic capture is recording")
_G_BYTES = METRICS.gauge(
    "pio_capture_journal_bytes",
    "on-disk bytes held by the capture journal (bounded drop-oldest)")


class CaptureRing:
    """Sampled request/response/provenance capture with journal spill."""

    def __init__(
        self,
        directory: str,
        *,
        sample: float = 1.0,
        ring_capacity: int = 256,
        max_bytes: int = 64 * 1024 * 1024,
        segment_max_bytes: int | None = None,
        enabled: bool = True,
    ):
        self.directory = str(directory)
        self.sample = min(1.0, max(0.0, float(sample)))
        self.ring_capacity = max(1, int(ring_capacity))
        # small segments relative to the cap: drop-oldest works at
        # segment granularity (only whole segments behind the cursor are
        # ever unlinked), so the journal must always have closed
        # segments to free when it fills
        seg = (int(segment_max_bytes) if segment_max_bytes
               else max(4096, int(max_bytes) // 16))
        self._journal = EventJournal(
            directory, fsync="batch",
            max_bytes=max(seg + 1, int(max_bytes)), segment_max_bytes=seg)
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque()
        self._rng = random.Random()
        self._closed = False
        self.enabled = bool(enabled)
        # lifetime counters (stats() mirrors the registry families)
        self.captured = 0
        self.sampled_out = 0
        self.dropped = 0
        self.flushes = 0
        _G_ENABLED.set(1 if self.enabled else 0)
        _G_BYTES.set(self._journal.size_bytes())

    # -- control -----------------------------------------------------------
    def start(self) -> None:
        self.enabled = True
        _G_ENABLED.set(1)

    def stop(self) -> None:
        """Disable recording and flush whatever the ring holds — a
        `pio capture stop` must leave everything captured so far on
        disk, not stranded in memory."""
        self.enabled = False
        _G_ENABLED.set(0)
        self.flush("manual")

    # -- hot path ----------------------------------------------------------
    def record(self, *, rid: str, request: dict, response,
               status: int, latency_ms: float,
               provenance: dict | None) -> None:
        """Capture one served request. Cheap by construction: a sample
        draw, one dict build, one deque append; the journal write is
        deferred to the next flush."""
        if self._closed or not self.enabled:
            return
        if self.sample < 1.0 and self._rng.random() >= self.sample:
            self.sampled_out += 1
            _M_RECORDS.inc(outcome="sampled_out")
            return
        rec = {
            "rid": rid,
            "ts": time.time(),
            "request": request,
            "response": response,
            "status": status,
            "latencyMs": round(latency_ms, 3),
            "provenance": provenance,
        }
        with self._lock:
            self._ring.append(rec)
            n = len(self._ring)
        self.captured += 1
        _M_RECORDS.inc(outcome="captured")
        _G_RING.set(n)
        if n >= self.ring_capacity:
            self.flush("ring_full")

    # -- persistence -------------------------------------------------------
    def flush(self, trigger: str = "manual") -> int:
        """Serialize the buffered ring into the capture journal. Returns
        the number of records persisted. Never raises — capture must not
        take serving down; failures count as drops."""
        with self._lock:
            if not self._ring:
                return 0
            batch, self._ring = list(self._ring), deque()
        _G_RING.set(0)
        persisted = 0
        for rec in batch:
            try:
                payload = json.dumps(rec, default=str,
                                     separators=(",", ":")).encode()
            except (TypeError, ValueError):
                self.dropped += 1
                _M_RECORDS.inc(outcome="dropped")
                continue
            if self._persist(payload):
                persisted += 1
            else:
                self.dropped += 1
                _M_RECORDS.inc(outcome="dropped")
        try:
            self._journal.sync()
        except Exception:  # noqa: BLE001 — durability is best-effort here
            log.exception("capture journal sync failed")
        self.flushes += 1
        _M_FLUSHES.inc(trigger=trigger)
        _G_BYTES.set(self._journal.size_bytes())
        return persisted

    def _persist(self, payload: bytes) -> bool:
        """Append with drop-oldest semantics: on ``JournalFull`` release
        the oldest captured records (cursor advance GCs whole segments
        behind it) and retry. Gives up when advancing frees nothing —
        the record is bigger than the journal, or everything left lives
        in the active segment."""
        for _ in range(64):
            try:
                self._journal.append(payload)
                return True
            except JournalFull:
                try:
                    recs, pos = self._journal.peek_batch(1024)
                except Exception:  # noqa: BLE001
                    return False
                if not recs:
                    return False
                before = self._journal.size_bytes()
                self._journal.advance(pos)
                if self._journal.size_bytes() >= before:
                    return False
            except Exception:  # noqa: BLE001 — a broken disk must not
                log.exception("capture journal append failed")  # kill serving
                return False
        return False

    # -- views / lifecycle -------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            ring = len(self._ring)
        j = self._journal.stats()
        return {
            "enabled": self.enabled,
            "directory": self.directory,
            "sample": self.sample,
            "ringRecords": ring,
            "ringCapacity": self.ring_capacity,
            "captured": self.captured,
            "sampledOut": self.sampled_out,
            "dropped": self.dropped,
            "flushes": self.flushes,
            "journalBytes": j["sizeBytes"],
            "journalMaxBytes": j["maxBytes"],
            "journalRecords": j["appended"],
            "journalSegments": j["segments"],
        }

    def close(self) -> None:
        """Final flush + journal close. Idempotent."""
        if self._closed:
            return
        self.flush("close")
        self._closed = True
        self.enabled = False
        _G_ENABLED.set(0)
        try:
            self._journal.close()
        except Exception:  # noqa: BLE001
            log.exception("capture journal close failed")


def iter_capture(directory: str) -> Iterator[dict]:
    """Yield every readable capture record (as a dict) from a capture
    directory, oldest first — a pure read-only scan over the journal
    segments (torn tails are skipped, never fatal), independent of the
    writer's drop-oldest cursor. Unparseable payloads are skipped."""
    for payload in iter_journal_records(Path(directory)):
        try:
            rec = json.loads(payload.decode())
        except (json.JSONDecodeError, UnicodeDecodeError):
            continue
        if isinstance(rec, dict):
            yield rec


def export_capture(directory: str, output: str) -> int:
    """Write a capture directory out as JSONL (one record per line) for
    `pio capture export`. Returns the record count."""
    n = 0
    with open(output, "w") as fh:
        for rec in iter_capture(directory):
            fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
            n += 1
    return n
