"""Request-scoped tracing: one id from ingress to the last side effect.

A request id is accepted from the ``X-PIO-Request-ID`` header or minted
at ingress, stored in a :mod:`contextvars` ContextVar (so it follows the
request across ``await`` points and into ``asyncio.to_thread`` workers,
which copy the context), and emitted in structured JSON log lines that
are joinable by ``trace``:

- query path: ingress → micro-batch queue wait → batched dispatch →
  device execute → feedback publish (the feedback event also carries a
  ``pio_request_id`` property so event-store rows join back);
- event path: ingress → journal append → drainer batch → backend
  upsert (the id rides inside the journal payload so a crash/replay
  keeps the join).

Lines go to the ``pio.trace`` logger as single-line JSON:
``{"evt": "serve.ingress", "trace": "ab12...", "ms": 1.93, ...}``.
``grep <trace-id>`` over the log is the whole query language.
"""

from __future__ import annotations

import contextvars
import json
import logging
import time
import uuid
from contextlib import contextmanager

__all__ = [
    "TRACE_HEADER",
    "current_request_id",
    "ensure_request_id",
    "new_request_id",
    "set_request_id",
    "span",
    "spans_from_waterfall",
    "render_span_tree",
    "trace_event",
]

#: the propagation header, accepted at ingress and echoed on responses
TRACE_HEADER = "X-PIO-Request-ID"

log = logging.getLogger("pio.trace")

_request_id: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_request_id", default=None)


def new_request_id() -> str:
    return uuid.uuid4().hex


def current_request_id() -> str | None:
    return _request_id.get()


def set_request_id(rid: str | None) -> contextvars.Token:
    return _request_id.set(rid)


def ensure_request_id(rid: str | None = None) -> str:
    """Adopt ``rid`` (e.g. from the ingress header), else keep the
    context's current id, else mint one. Returns the id now in effect."""
    got = rid or _request_id.get()
    if not got:
        got = new_request_id()
    _request_id.set(got)
    return got


def trace_event(evt: str, *, trace: str | None = None, **fields) -> None:
    """Emit one structured line. ``trace`` overrides the context id (a
    batched dispatch logs once with every member id instead)."""
    rec = {"evt": evt, "trace": trace or _request_id.get()}
    rec.update(fields)
    log.info("%s", json.dumps(rec, sort_keys=True, default=str))


@contextmanager
def span(evt: str, *, trace: str | None = None, **fields):
    """Time a block and emit one line with its duration in ms. Yields a
    dict the block may add fields to (e.g. row counts learned mid-span)."""
    extra: dict = {}
    t0 = time.perf_counter()
    try:
        yield extra
    except BaseException as e:
        extra["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        ms = (time.perf_counter() - t0) * 1e3
        trace_event(evt, trace=trace, ms=round(ms, 3), **{**fields, **extra})


# ---------------------------------------------------------------------------
# Span-tree assembly (``pio trace <rid>``): the propagation above makes a
# request id joinable across processes; these helpers turn the joined
# pieces — router hop, replica waterfalls, ingest WAL records — into one
# rendered tree. A node is ``{"label": str, "ms": float|None,
# "detail": str|None, "children": [node, ...]}``.

def spans_from_waterfall(record: dict, label: str | None = None) -> dict:
    """One flight-recorder waterfall record (``Waterfall.to_dict()``
    shape) as a span node: the request wall at the top, one child per
    attributed stage in canonical order."""
    stages = record.get("stagesMs") or {}
    details = []
    if record.get("status"):
        details.append(f"status={record['status']}")
    if record.get("stalledStage"):
        details.append(f"stalled={record['stalledStage']}")
    if not record.get("finished", True):
        details.append("unfinished")
    return {
        "label": label or f"{record.get('path', 'serve')} request",
        "ms": record.get("wallMs"),
        "detail": " ".join(details) or None,
        "children": [{"label": s, "ms": ms, "detail": None, "children": []}
                     for s, ms in stages.items()],
    }


def render_span_tree(nodes: list[dict], title: str | None = None) -> str:
    """ASCII tree of span nodes, durations right-aligned to the label."""
    lines: list[str] = []
    if title:
        lines.append(title)

    def fmt(node: dict) -> str:
        parts = [str(node.get("label", "?"))]
        ms = node.get("ms")
        if ms is not None:
            parts.append(f"{float(ms):.3f} ms")
        if node.get("detail"):
            parts.append(f"[{node['detail']}]")
        return "  ".join(parts)

    def walk(node: dict, prefix: str, last: bool, root: bool) -> None:
        if root:
            lines.append(fmt(node))
            child_prefix = ""
        else:
            lines.append(f"{prefix}{'└─ ' if last else '├─ '}{fmt(node)}")
            child_prefix = prefix + ("   " if last else "│  ")
        kids = node.get("children") or []
        for i, kid in enumerate(kids):
            walk(kid, child_prefix, i == len(kids) - 1, False)

    for node in nodes:
        walk(node, "", True, True)
    return "\n".join(lines)
