"""Convergence telemetry for batch training and streaming fold-in (ISSUE 12).

"Is this ALS run converging or just burning iterations?" — the tracker
collects, per source (``"train"`` for the batch ALS loop, ``"stream"``
for the journal-tailing updater), a bounded per-iteration history of
step time, sampled-holdout loss, and factor-delta norm, surfaces the
live values as ``pio_train_convergence_*`` gauges, and summarizes each
finished attempt for the EngineInstance record (``pio status`` prints
the summary; the dashboard's ``/train.json`` proxies the snapshot).

Like the ledger, this is pure bookkeeping and must never take down a
training run: every public method swallows its own errors.
"""

from __future__ import annotations

import threading

from .metrics import METRICS

_G_LOSS = METRICS.gauge(
    "pio_train_convergence_loss",
    "latest sampled-holdout loss (RMSE over the sampled ratings for "
    "ALS training; gate metric for streaming)",
    labelnames=("source",))

_G_DELTA = METRICS.gauge(
    "pio_train_convergence_delta_norm",
    "latest relative factor-delta norm ||x_t - x_{t-1}|| / ||x_{t-1}|| "
    "— the direct convergence signal (0 = fixed point)",
    labelnames=("source",))

_G_ITERATION = METRICS.gauge(
    "pio_train_convergence_iteration",
    "latest completed iteration (train) or cycle (stream) number",
    labelnames=("source",))

#: per-source iteration history kept for the dashboard; summaries only
#: need aggregates, so a small bound is plenty
HISTORY_LIMIT = 256


class ConvergenceTracker:
    """Process-wide convergence telemetry, one channel per source."""

    def __init__(self):
        self._lock = threading.Lock()
        self._live: dict[str, dict] = {}
        self._attempts: dict[str, list[dict]] = {}

    def begin(self, source: str, total_iterations: int | None = None) -> None:
        """Open a fresh attempt for ``source`` (prior live state is
        finalized as "superseded" if it never finished)."""
        try:
            with self._lock:
                live = self._live.get(source)
                if live is not None and live["history"]:
                    self._finish_locked(source, "superseded")
                self._live[source] = {
                    "totalIterations": total_iterations,
                    "history": [],
                    "iterations": 0,
                }
        except Exception:
            pass

    def observe(self, source: str, iteration: int, *,
                loss: float | None = None,
                delta_norm: float | None = None,
                step_seconds: float | None = None) -> None:
        """Record one completed iteration/cycle. ``None`` fields are
        simply absent (e.g. the loss sampler was disabled)."""
        try:
            rec = {"iteration": int(iteration)}
            if loss is not None:
                rec["loss"] = float(loss)
                _G_LOSS.set(float(loss), source=source)
            if delta_norm is not None:
                rec["deltaNorm"] = float(delta_norm)
                _G_DELTA.set(float(delta_norm), source=source)
            if step_seconds is not None:
                rec["stepSeconds"] = float(step_seconds)
            _G_ITERATION.set(float(iteration), source=source)
            with self._lock:
                live = self._live.get(source)
                if live is None:
                    live = {"totalIterations": None, "history": [],
                            "iterations": 0}
                    self._live[source] = live
                live["history"].append(rec)
                del live["history"][:-HISTORY_LIMIT]
                live["iterations"] = max(live["iterations"],
                                         int(iteration) + 1)
        except Exception:
            pass

    def finish(self, source: str, status: str = "COMPLETED") -> None:
        """Close the live attempt into the per-source summary list."""
        try:
            with self._lock:
                self._finish_locked(source, status)
        except Exception:
            pass

    def _finish_locked(self, source: str, status: str) -> None:
        live = self._live.pop(source, None)
        if live is None:
            return
        self._attempts.setdefault(source, []).append(
            _summarize(live, status))

    def summaries(self, source: str) -> list[dict]:
        """Finished-attempt summaries, oldest first — the JSON stamped
        into ``EngineInstance.convergence``."""
        with self._lock:
            return [dict(s) for s in self._attempts.get(source, [])]

    def snapshot(self) -> dict:
        """Dashboard/stats view: live history + finished attempts."""
        with self._lock:
            out: dict = {}
            for source in set(self._live) | set(self._attempts):
                live = self._live.get(source)
                out[source] = {
                    "live": {
                        "totalIterations": live["totalIterations"],
                        "iterations": live["iterations"],
                        "history": list(live["history"][-32:]),
                    } if live is not None else None,
                    "attempts": [dict(s)
                                 for s in self._attempts.get(source, [])],
                }
            return out

    def reset_source(self, source: str) -> None:
        """Drop everything for one source (a fresh run_train attempt
        must not inherit a previous run's attempt summaries)."""
        with self._lock:
            self._live.pop(source, None)
            self._attempts.pop(source, None)

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._attempts.clear()


def _summarize(live: dict, status: str) -> dict:
    hist = live["history"]
    losses = [r["loss"] for r in hist if "loss" in r]
    steps = [r["stepSeconds"] for r in hist if "stepSeconds" in r]
    deltas = [r["deltaNorm"] for r in hist if "deltaNorm" in r]
    return {
        "status": status,
        "iterations": live["iterations"],
        "totalIterations": live["totalIterations"],
        "finalLoss": losses[-1] if losses else None,
        "firstLoss": losses[0] if losses else None,
        "finalDeltaNorm": deltas[-1] if deltas else None,
        "meanStepSeconds": (sum(steps) / len(steps)) if steps else None,
    }


#: process-wide singleton, mirroring METRICS / FLIGHT / LEDGER
TRAINING = ConvergenceTracker()
