"""Deterministic replay + shadow diff over captured golden traffic.

ISSUE 13, the verification half of the capture/replay harness
(obs/capture.py records; this module re-issues and diffs):

- ``replay_records()`` re-issues captured queries against a LIVE server
  (``pio replay <capture> --target URL``) or an in-process engine
  (``--engine-instance-id``) and classifies every answer pair at three
  tiers, strictest first:

  1. **bitwise** — identical payload: same item ids in the same order
     with float-identical scores (JSON round-trip equality). The parity
     a refactor must hold to call itself a refactor.
  2. **topk_set** — the same item SET, but order or scores moved: a
     tie-break or reduction-order change, not a wrong answer.
  3. **score_tol** — the score ladder matches within tolerance but the
     items differ: equivalently-scored alternatives swapped in (ANN
     probe order, quantization). Worth eyes, rarely a bug.
  4. **mismatch** — none of the above: the answers genuinely differ
     (e.g. a delta patch moved this user's factors).

  The report keys every mismatch by its request and by the provenance
  delta between capture time and replay time, so "what changed" reads
  straight off the report (patch epoch bump, different blob sha, ...).

- ``ShadowMirror`` mirrors sampled LIVE traffic to a second instance
  (``pio deploy --shadow-target URL``) and publishes the same tier
  classification as online metrics (``pio_shadow_diff_total{tier}``,
  ``pio_shadow_lag_seconds``). Fire-and-forget through the same
  bounded-tracked-task discipline as ``workflow/feedback.py``'s
  FeedbackPublisher: one shared ClientSession, every task tracked and
  awaited at drain, a hard in-flight bound that DROPS (counted) instead
  of queueing — the mirror can never slow or wedge the primary.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
import urllib.request

from .metrics import METRICS
from .trace import TRACE_HEADER

log = logging.getLogger("predictionio_tpu.replay")

__all__ = ["diff_tier", "replay_records", "ShadowMirror",
           "PROVENANCE_HEADER", "VARIANT_HEADER", "TIERS"]

#: compact-JSON provenance envelope stamped on every serving response
#: (workflow/create_server.py) — replay reads it back from live targets
PROVENANCE_HEADER = "X-PIO-Provenance"

#: forced-routing override (ISSUE 14, workflow/variants.py): replay
#: stamps each record's captured variant id here so the replayed query
#: re-hits the variant that originally answered it, not the hash bucket
#: the target's CURRENT weights would pick
VARIANT_HEADER = "X-PIO-Variant"

TIERS = ("bitwise", "topk_set", "score_tol", "mismatch", "error")

_M_SHADOW_DIFF = METRICS.counter(
    "pio_shadow_diff_total",
    "shadow-mirrored responses by diff tier vs the primary "
    "(bitwise/topk_set/score_tol/mismatch/error)",
    labelnames=("tier",))
_M_SHADOW_LAG = METRICS.gauge(
    "pio_shadow_lag_seconds",
    "latest shadow response time measured from the primary's answer "
    "(how far the shadow trails live traffic)")
_M_SHADOW = METRICS.counter(
    "pio_shadow_mirrored_total",
    "shadow mirror decisions (mirrored/sampled_out/dropped)",
    labelnames=("outcome",))


# -- diffing ---------------------------------------------------------------

def _item_scores(payload) -> list[tuple[object, float]] | None:
    """Extract an ordered ``[(item, score), ...]`` ranking from a
    serving payload. Understands the ``itemScores`` convention the
    recommendation templates serve; returns None for anything else (the
    differ falls back to whole-payload equality)."""
    if not isinstance(payload, dict):
        return None
    rows = payload.get("itemScores")
    if not isinstance(rows, list):
        return None
    out = []
    for row in rows:
        if not isinstance(row, dict) or "score" not in row:
            return None
        item = row.get("item", row.get("id"))
        try:
            out.append((item, float(row["score"])))
        except (TypeError, ValueError):
            return None
    return out


def diff_tier(captured, replayed, score_tol: float = 1e-6) -> str:
    """Classify one captured/replayed response pair into the strictest
    matching tier (see module docstring)."""
    if captured == replayed:
        return "bitwise"
    a, b = _item_scores(captured), _item_scores(replayed)
    if a is None or b is None:
        return "mismatch"  # opaque payloads that differ at all differ
    if a == b:
        return "bitwise"  # rankings identical; some other field moved
    if {i for i, _ in a} == {i for i, _ in b}:
        return "topk_set"
    if len(a) == len(b) and all(
            abs(sa - sb) <= score_tol * max(1.0, abs(sa))
            for (_, sa), (_, sb) in zip(a, b)):
        return "score_tol"
    return "mismatch"


def _provenance_delta(captured: dict | None,
                      replayed: dict | None) -> dict:
    """Field-level diff of two provenance envelopes:
    ``{field: {"captured": x, "replayed": y}}`` for every field that
    moved — the "what changed between capture and replay" answer."""
    captured, replayed = captured or {}, replayed or {}
    delta = {}
    for key in sorted(set(captured) | set(replayed)):
        if captured.get(key) != replayed.get(key):
            delta[key] = {"captured": captured.get(key),
                          "replayed": replayed.get(key)}
    return delta


# -- replay ----------------------------------------------------------------

def _http_issue(target: str, timeout_s: float):
    """Issuer re-POSTing each captured query to a live ``target`` —
    returns ``(response, provenance, ok)``; provenance comes back off
    the X-PIO-Provenance response header."""
    base = target.rstrip("/")

    def issue(record: dict):
        headers = {"Content-Type": "application/json",
                   TRACE_HEADER: f"replay-{record.get('rid', '')}"}
        # ISSUE 14: pin the replay to the variant that answered the
        # captured request — a multi-variant target must not re-hash
        # the query into whatever its current weights say
        vid = (record.get("provenance") or {}).get("variantId")
        if vid:
            headers[VARIANT_HEADER] = str(vid)
        req = urllib.request.Request(
            f"{base}/queries.json",
            data=json.dumps(record["request"]).encode(),
            headers=headers,
            method="POST")
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = json.loads(resp.read().decode())
            prov_hdr = resp.headers.get(PROVENANCE_HEADER)
        prov = None
        if prov_hdr:
            try:
                prov = json.loads(prov_hdr)
            except json.JSONDecodeError:
                prov = None
        return body, prov, True

    return issue


def _server_issue(server):
    """Issuer dispatching each captured query through an in-process
    ``EngineServer`` (no HTTP): the `pio replay --engine-instance-id`
    path, same rehydrated-bundle serving `pio batchpredict` uses."""

    def issue(record: dict):
        body = server.serve_query(record["request"])
        return body, server.provenance(), True

    return issue


def replay_records(records, *, target: str | None = None, server=None,
                   score_tol: float = 1e-6, timeout_s: float = 10.0,
                   mismatch_cap: int = 256) -> dict:
    """Re-issue captured traffic and produce the parity/latency report.

    ``records``: iterable of capture dicts (obs/capture.iter_capture).
    Exactly one of ``target`` (live server base URL) or ``server``
    (in-process EngineServer) must be given. Only records captured with
    HTTP status 200 are replayed — error answers aren't parity targets.
    """
    if (target is None) == (server is None):
        raise ValueError("replay needs exactly one of target= or server=")
    issue = _http_issue(target, timeout_s) if target else _server_issue(server)
    tiers = {t: 0 for t in TIERS}
    # ISSUE 14: parity grouped by the variant that answered at capture
    # time — the A/B diff reads straight off one capture
    by_variant: dict[str, dict] = {}

    def _vtier(rec: dict, tier: str) -> None:
        vid = str((rec.get("provenance") or {}).get("variantId")
                  or "default")
        vt = by_variant.setdefault(
            vid, {"total": 0, "tiers": {t: 0 for t in TIERS}})
        vt["total"] += 1
        vt["tiers"][tier] += 1

    mismatches: list[dict] = []
    captured_ms: list[float] = []
    replayed_ms: list[float] = []
    replay_prov: dict | None = None
    capture_prov: dict | None = None
    skipped = total = 0
    for rec in records:
        if not isinstance(rec.get("request"), dict) \
                or rec.get("status", 200) != 200:
            skipped += 1
            continue
        total += 1
        if capture_prov is None and isinstance(rec.get("provenance"), dict):
            capture_prov = rec["provenance"]
        t0 = time.perf_counter()
        try:
            body, prov, _ok = issue(rec)
        except Exception as e:  # noqa: BLE001 — report, don't die mid-run
            tiers["error"] += 1
            _vtier(rec, "error")
            if len(mismatches) < mismatch_cap:
                mismatches.append({"rid": rec.get("rid"),
                                   "tier": "error",
                                   "request": rec["request"],
                                   "error": f"{type(e).__name__}: {e}"})
            continue
        replayed_ms.append((time.perf_counter() - t0) * 1e3)
        if isinstance(rec.get("latencyMs"), (int, float)):
            captured_ms.append(float(rec["latencyMs"]))
        if prov is not None:
            replay_prov = prov
        # the feedback loop decorates live answers with a prId the
        # replay target won't reproduce — strip it on both sides
        tier = diff_tier(_strip_volatile(rec.get("response")),
                         _strip_volatile(body), score_tol)
        tiers[tier] += 1
        _vtier(rec, tier)
        if tier != "bitwise" and len(mismatches) < mismatch_cap:
            mismatches.append({
                "rid": rec.get("rid"),
                "tier": tier,
                "request": rec["request"],
                "captured": rec.get("response"),
                "replayed": body,
                "provenanceDelta": _provenance_delta(
                    rec.get("provenance"), prov),
            })
    return {
        "total": total,
        "skipped": skipped,
        "tiers": tiers,
        "parityPct": round(100.0 * tiers["bitwise"] / total, 3) if total else None,
        "scoreTol": score_tol,
        "latencyMs": {"captured": _p50(captured_ms),
                      "replayed": _p50(replayed_ms)},
        "provenance": {
            "captured": capture_prov,
            "replayed": replay_prov,
            "delta": _provenance_delta(capture_prov, replay_prov),
        },
        "variants": {
            vid: {**vt,
                  "parityPct": (round(
                      100.0 * vt["tiers"]["bitwise"] / vt["total"], 3)
                      if vt["total"] else None)}
            for vid, vt in sorted(by_variant.items())
        },
        "mismatches": mismatches,
    }


def _strip_volatile(payload):
    """Drop per-request fields no replay can reproduce (the feedback
    prId is minted fresh per serve)."""
    if isinstance(payload, dict) and "prId" in payload:
        return {k: v for k, v in payload.items() if k != "prId"}
    return payload


def _p50(xs: list[float]) -> float | None:
    if not xs:
        return None
    s = sorted(xs)
    return round(s[len(s) // 2], 3)


# -- shadow mirror ---------------------------------------------------------

class ShadowMirror:
    """Mirror sampled live traffic to a second instance, diff online.

    The FeedbackPublisher discipline, minus the retry queue (a shadow
    answer is only meaningful NOW — replaying it later would diff stale
    traffic against a moved target): one shared session, tracked tasks
    cancelled+awaited at drain, a hard in-flight bound that drops
    (counted) rather than queues. ``mirror()`` is synchronous and
    allocation-light; everything slow happens inside the task.
    """

    def __init__(self, target: str, *, sample: float = 1.0,
                 max_inflight: int = 64, timeout_s: float = 5.0,
                 score_tol: float = 1e-6):
        self.target = target.rstrip("/")
        self.sample = min(1.0, max(0.0, float(sample)))
        self.max_inflight = max(1, int(max_inflight))
        self.timeout_s = timeout_s
        self.score_tol = score_tol
        self._rng = random.Random()
        self._session = None
        self._tasks: set[asyncio.Task] = set()
        self._closing = False
        self.mirrored = 0
        self.dropped = 0
        self.tiers = {t: 0 for t in TIERS}

    # -- hot path ----------------------------------------------------------
    def mirror(self, query_json: dict, primary_response, rid: str) -> None:
        """Fire-and-forget mirror of one served query. Never blocks the
        caller: over the in-flight bound (shadow slower than primary),
        the sample is dropped and counted."""
        if self._closing:
            return
        if self.sample < 1.0 and self._rng.random() >= self.sample:
            _M_SHADOW.inc(outcome="sampled_out")
            return
        if len(self._tasks) >= self.max_inflight:
            self.dropped += 1
            _M_SHADOW.inc(outcome="dropped")
            return
        task = asyncio.create_task(
            self._mirror_one(query_json, primary_response, rid,
                             time.monotonic()))
        self._tasks.add(task)
        task.add_done_callback(self._task_done)

    def _task_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()  # retrieve: a lost exception logs nothing
        if exc is not None:
            log.warning("shadow mirror task died: %s", exc)

    async def _ensure_session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        return self._session

    async def _mirror_one(self, query_json: dict, primary, rid: str,
                          t0: float) -> None:
        try:
            session = await self._ensure_session()
            async with session.post(
                f"{self.target}/queries.json", json=query_json,
                headers={TRACE_HEADER: f"shadow-{rid}"},
            ) as resp:
                body = await resp.json()
                ok = resp.status == 200
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — an unreachable shadow is a tier
            self.tiers["error"] += 1
            _M_SHADOW_DIFF.inc(tier="error")
            return
        _M_SHADOW_LAG.set(time.monotonic() - t0)
        tier = (diff_tier(_strip_volatile(primary), _strip_volatile(body),
                          self.score_tol) if ok else "error")
        self.mirrored += 1
        self.tiers[tier] += 1
        _M_SHADOW.inc(outcome="mirrored")
        _M_SHADOW_DIFF.inc(tier=tier)

    # -- lifecycle ---------------------------------------------------------
    async def aclose(self) -> None:
        """Drain-time teardown: cancel + await every tracked task, close
        the shared session. Idempotent."""
        self._closing = True
        tasks, self._tasks = set(self._tasks), set()
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        if self._session is not None and not self._session.closed:
            await self._session.close()
        self._session = None

    def stats(self) -> dict:
        return {
            "target": self.target,
            "sample": self.sample,
            "mirrored": self.mirrored,
            "dropped": self.dropped,
            "inflight": len(self._tasks),
            "tiers": dict(self.tiers),
        }
