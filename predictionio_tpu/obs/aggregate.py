"""Fleet observability plane: exact cross-replica metric aggregation.

PRs 17–19 made serving a real multi-process fleet, but every PR-5/11/12
observability surface — ``/metrics``, SLO burn, waterfalls, ``pio top``
— is per-process. This module gives the router a :class:`FleetCollector`
that rides the probe loop, scrapes each replica's ``/metrics`` +
``/stats.json`` (the HTTP lives in ``workflow/fleet.py``; this module is
pure functions over scraped text so it unit-tests without a socket), and
merges them **exactly**:

- **counters** sum per (family, label set);
- **gauges** keep per-replica identity plus min/max/sum rollups (a mean
  of ``pio_server_mode`` would be meaningless — per-replica is the
  truth, the rollup is the convenience);
- **histograms** merge bucket-wise and *bitwise*: every process buckets
  latency with the same ``DEFAULT_TIME_BUCKETS_S`` table
  (obs/metrics.py), so summing integer bucket counts and interpolating
  with the shared :func:`~predictionio_tpu.obs.metrics.quantile_from_counts`
  reproduces EXACTLY the histogram a single process fed the union of
  samples would report. No approximation, no averaged percentiles. A
  bucket-bounds mismatch (version skew during a rolling deploy) drops
  that family with ``pio_fleet_merge_dropped_total`` — never a crash.

On top of the merged snapshot the collector derives per-replica
*windowed* signals (qps, p50/p99, error fraction, shed rate — deltas
between consecutive scrapes, so they describe "now", not the process
lifetime) and flags **outliers**: a replica whose signal deviates from
the fleet median beyond ``outlier_band`` (plus a per-signal absolute
floor, so a 0.2 ms fleet doesn't flag a 0.3 ms replica) gets
``pio_fleet_outlier{replica,signal}`` = 1.

Staleness contract (collector hygiene): a failed scrape keeps the
replica's last snapshot; every view stamps it with ``ageSeconds`` and a
snapshot older than ``stale_after_s`` is excluded from merges, medians
and the fleet SLO — the surviving replicas keep serving fleet truth
with no gap.
"""

from __future__ import annotations

import math
import statistics
import threading
import time

from .metrics import (METRICS, _fmt_labels, _fmt_value,
                      quantile_from_counts)
from .slo import merge_slo_summaries

__all__ = [
    "FleetCollector",
    "parse_prometheus",
    "merge_histograms",
    "fleet_snapshot",
]

_C_MERGE_DROPPED = METRICS.counter(
    "pio_fleet_merge_dropped_total",
    "histogram families dropped from the fleet merge because replicas "
    "disagree on bucket bounds (version skew)",
    labelnames=("family",))
_C_SCRAPE_FAILURES = METRICS.counter(
    "pio_fleet_scrape_failures_total",
    "replica metric scrapes that failed or timed out (the last good "
    "snapshot is kept and ages out)",
    labelnames=("replica",))
_G_SCRAPE_AGE = METRICS.gauge(
    "pio_fleet_scrape_age_seconds",
    "age of each replica's last successful metrics scrape",
    labelnames=("replica",))
_G_OUTLIER = METRICS.gauge(
    "pio_fleet_outlier",
    "1 when a replica's windowed signal (p99 / errorFraction / "
    "shedRate) deviates from the fleet median beyond the outlier band",
    labelnames=("replica", "signal"))
_G_FRESH = METRICS.gauge(
    "pio_fleet_replicas_fresh",
    "replicas whose metrics snapshot is fresh enough to merge")

#: request outcomes counted as load-shedding rather than errors when
#: deriving the windowed error fraction from ``pio_queries_total``
_SHED_STATUSES = frozenset({"shed", "busy", "draining", "throttle"})

#: absolute floors added to the median band per outlier signal, so a
#: uniformly fast/healthy fleet never flags noise-level deviations
_SIGNAL_FLOORS = {"p99": 1e-3, "errorFraction": 0.05, "shedRate": 0.05}


# ---------------------------------------------------------------------------
# Prometheus text exposition parsing (v0.0.4, as rendered by obs/metrics).

def _parse_labelset(s: str) -> tuple[tuple[str, str], ...]:
    """``a="x",b="y"`` (brace-stripped) -> (("a","x"),("b","y")).
    Handles the renderer's escapes: ``\\\\``, ``\\"``, ``\\n``."""
    out: list[tuple[str, str]] = []
    i, n = 0, len(s)
    while i < n:
        j = s.index("=", i)
        name = s[i:j].strip()
        if j + 1 >= n or s[j + 1] != '"':
            raise ValueError(f"bad label at {i}: {s!r}")
        i = j + 2
        buf: list[str] = []
        while True:
            ch = s[i]
            if ch == "\\":
                nxt = s[i + 1]
                buf.append("\n" if nxt == "n" else nxt)
                i += 2
            elif ch == '"':
                i += 1
                break
            else:
                buf.append(ch)
                i += 1
        out.append((name, "".join(buf)))
        if i < n and s[i] == ",":
            i += 1
    return tuple(out)


def _split_series(line: str):
    """One sample line -> (metric_name, label tuple, float value)."""
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        name = line[:brace]
        # scan to the closing brace with quote awareness: label values
        # may contain '}' inside quotes
        i, in_str, esc = brace + 1, False, False
        while i < len(line):
            ch = line[i]
            if in_str:
                if esc:
                    esc = False
                elif ch == "\\":
                    esc = True
                elif ch == '"':
                    in_str = False
            elif ch == '"':
                in_str = True
            elif ch == "}":
                break
            i += 1
        labels = _parse_labelset(line[brace + 1:i])
        rest = line[i + 1:].strip()
    else:
        name, _, rest = line.partition(" ")
        labels = ()
        rest = rest.strip()
    value_str = rest.split()[0]  # an optional timestamp may follow
    return name, labels, float("inf" if value_str == "+Inf" else value_str)


def parse_prometheus(text: str) -> dict:
    """Parse one process's ``/metrics`` page back into structure::

        {"counters":   {name: {labels_tuple: value}},
         "gauges":     {name: {labels_tuple: value}},
         "histograms": {name: {"bounds": (...), "counts": (raw..., incl
                        overflow last), "count": int, "sum": float}},
         "help":       {name: help_text}}

    The derived ``*_summary`` sibling families the renderer emits are
    skipped (they are views of the histograms, not independent data).
    Bucket bounds round-trip bitwise: ``repr(float)`` -> ``float()`` is
    exact, so cross-replica bounds comparison is an exact float compare.
    Unparseable lines are skipped, never fatal — a half-written page
    costs one scrape, not the collector.
    """
    types: dict[str, str] = {}
    helps: dict[str, str] = {}
    counters: dict[str, dict] = {}
    gauges: dict[str, dict] = {}
    hist_raw: dict[str, dict] = {}

    def _hist(base: str) -> dict:
        return hist_raw.setdefault(
            base, {"buckets": {}, "sum": 0.0, "count": 0})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        try:
            name, labels, value = _split_series(line)
        except (ValueError, IndexError):
            continue
        if types.get(name) == "summary":
            continue  # quantile lines of a *_summary sibling
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[:-len(suffix)]
                if types.get(base) == "histogram":
                    h = _hist(base)
                    if suffix == "_bucket":
                        le = dict(labels).get("le")
                        if le is not None:
                            bound = (math.inf if le == "+Inf"
                                     else float(le))
                            h["buckets"][bound] = value
                    elif suffix == "_sum":
                        h["sum"] = value
                    else:
                        h["count"] = int(value)
                    break
                if types.get(base) == "summary":
                    break
        else:
            kind = types.get(name)
            if kind == "counter":
                counters.setdefault(name, {})[labels] = value
            elif kind != "histogram":  # gauge or untyped
                gauges.setdefault(name, {})[labels] = value
            continue
        continue

    histograms: dict[str, dict] = {}
    for name, h in hist_raw.items():
        bounds = tuple(sorted(b for b in h["buckets"] if b != math.inf))
        cum = [h["buckets"][b] for b in bounds]
        total = int(h["buckets"].get(math.inf, h["count"]))
        raw: list[int] = []
        prev = 0.0
        for c in cum:
            raw.append(max(0, int(round(c - prev))))
            prev = c
        raw.append(max(0, int(round(total - prev))))
        histograms[name] = {"bounds": bounds, "counts": tuple(raw),
                            "count": total, "sum": float(h["sum"])}
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms, "help": helps}


# ---------------------------------------------------------------------------
# Exact merge.

def merge_histograms(per_replica: dict[str, dict],
                     on_drop=None) -> dict:
    """Merge ``{replica: parsed["histograms"]}`` bucket-wise. Families
    whose bucket bounds differ across replicas are dropped whole (the
    merged numbers would be lies); ``on_drop(family)`` is told."""
    merged: dict[str, dict] = {}
    dropped: set[str] = set()
    for rep in sorted(per_replica):
        for name, h in per_replica[rep].items():
            if name in dropped:
                continue
            m = merged.get(name)
            if m is None:
                merged[name] = {"bounds": h["bounds"],
                                "counts": list(h["counts"]),
                                "count": h["count"], "sum": h["sum"]}
                continue
            if m["bounds"] != h["bounds"] or (
                    len(m["counts"]) != len(h["counts"])):
                del merged[name]
                dropped.add(name)
                if on_drop is not None:
                    on_drop(name)
                continue
            m["counts"] = [a + b for a, b in zip(m["counts"], h["counts"])]
            m["count"] += h["count"]
            m["sum"] += h["sum"]
    for m in merged.values():
        m["counts"] = tuple(m["counts"])
    return merged


def fleet_snapshot(parsed_by_replica: dict[str, dict],
                   on_drop=None) -> dict:
    """The merged JSON view (``/fleet/stats.json`` core)::

        {"counters":   {"name{labels}": summed_value},
         "gauges":     {"name{labels}": {min,max,sum,byReplica}},
         "histograms": {name: {count,sum,p50,p95,p99}}}

    Histogram quantiles come from :func:`quantile_from_counts` over the
    merged integer bucket counts — the same function every process's
    ``Histogram`` uses, so they equal the union-fed histogram exactly.
    """
    counters: dict[str, float] = {}
    gauges: dict[str, dict] = {}
    for rep in sorted(parsed_by_replica):
        parsed = parsed_by_replica[rep]
        for name, series in parsed["counters"].items():
            for labels, v in series.items():
                key = name + _fmt_labels(tuple(n for n, _ in labels),
                                         tuple(v_ for _, v_ in labels))
                counters[key] = counters.get(key, 0.0) + v
        for name, series in parsed["gauges"].items():
            for labels, v in series.items():
                key = name + _fmt_labels(tuple(n for n, _ in labels),
                                         tuple(v_ for _, v_ in labels))
                g = gauges.setdefault(
                    key, {"min": v, "max": v, "sum": 0.0, "byReplica": {}})
                g["min"] = min(g["min"], v)
                g["max"] = max(g["max"], v)
                g["sum"] += v
                g["byReplica"][rep] = v
    merged_h = merge_histograms(
        {rep: p["histograms"] for rep, p in parsed_by_replica.items()},
        on_drop=on_drop)
    histograms = {
        name: {
            "count": m["count"],
            "sum": m["sum"],
            "p50": quantile_from_counts(m["bounds"], m["counts"], 0.50),
            "p95": quantile_from_counts(m["bounds"], m["counts"], 0.95),
            "p99": quantile_from_counts(m["bounds"], m["counts"], 0.99),
        }
        for name, m in sorted(merged_h.items())
    }
    return {"counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": histograms}


# ---------------------------------------------------------------------------
# The collector.

class _ReplicaSample:
    __slots__ = ("parsed", "stats", "mono", "wall", "scrapes", "failures",
                 "last_error", "window", "prev_serve", "prev_queries",
                 "flight_dumps")

    def __init__(self) -> None:
        self.parsed: dict | None = None
        self.stats: dict = {}
        self.mono: float | None = None   # monotonic time of last GOOD scrape
        self.wall: float | None = None
        self.scrapes = 0
        self.failures = 0
        self.last_error: str | None = None
        self.window: dict = {}
        self.prev_serve: tuple | None = None     # (bounds, counts, mono)
        self.prev_queries: dict | None = None    # labels -> value
        self.flight_dumps: int | None = None


class FleetCollector:
    """Router-side scrape state + exact merge + outlier flags.

    The router feeds it (:meth:`ingest` on scrape success,
    :meth:`mark_failed` on failure) from the probe loop; the
    ``/fleet/*`` handlers and ``pio fleet status`` read the merged
    views. Thread-safe: the bench drives it from worker threads.
    """

    #: histogram the windowed p50/p99/qps signals derive from
    SERVE_HISTOGRAM = "pio_serving_latency_seconds"
    QUERIES_COUNTER = "pio_queries_total"

    def __init__(self, stale_after_s: float = 10.0,
                 outlier_band: float = 0.75,
                 min_window_events: int = 20,
                 now_fn=time.monotonic, wall_fn=time.time):
        self.stale_after_s = float(stale_after_s)
        self.outlier_band = float(outlier_band)
        self.min_window_events = int(min_window_events)
        self._now = now_fn
        self._wall = wall_fn
        self._lock = threading.Lock()
        self._samples: dict[str, _ReplicaSample] = {}
        self._outlier_keys: set[tuple[str, str]] = set()
        self._dropped_families: set[str] = set()

    # -- feeding -------------------------------------------------------
    def ingest(self, replica: str, metrics_text: str,
               stats: dict | None = None) -> bool:
        """Book one successful scrape. Returns True when the replica's
        flight recorder fired since the previous scrape (its ``dumps``
        count advanced) — the router's cue to pull ``/debug/flight.json``
        and write a correlated fleet incident bundle."""
        parsed = parse_prometheus(metrics_text)
        stats = stats or {}
        now = self._now()
        with self._lock:
            s = self._samples.setdefault(replica, _ReplicaSample())
            prev_dumps = s.flight_dumps
            self._update_window_locked(s, parsed, now)
            s.parsed = parsed
            s.stats = stats
            s.mono = now
            s.wall = self._wall()
            s.scrapes += 1
            s.last_error = None
            dumps = ((stats.get("flight") or {}).get("dumps")
                     if isinstance(stats.get("flight"), dict) else None)
            if isinstance(dumps, (int, float)):
                s.flight_dumps = int(dumps)
            fired = (prev_dumps is not None
                     and s.flight_dumps is not None
                     and s.flight_dumps > prev_dumps)
        self._refresh_meta_gauges()
        return fired

    def mark_failed(self, replica: str, error: str) -> None:
        """A scrape failed or timed out: keep the last snapshot (it ages
        out of merges past ``stale_after_s``), count the failure."""
        with self._lock:
            s = self._samples.setdefault(replica, _ReplicaSample())
            s.failures += 1
            s.last_error = error
        _C_SCRAPE_FAILURES.inc(replica=replica)
        self._refresh_meta_gauges()

    def forget(self, replica: str) -> None:
        """Drop a replica that left the fleet for good."""
        with self._lock:
            self._samples.pop(replica, None)

    def _update_window_locked(self, s: _ReplicaSample, parsed: dict,
                              now: float) -> None:
        """Windowed signals: deltas between consecutive scrapes."""
        window: dict = {}
        h = parsed["histograms"].get(self.SERVE_HISTOGRAM)
        if h is not None:
            if (s.prev_serve is not None and s.mono is not None
                    and s.prev_serve[0] == h["bounds"]):
                dt = max(now - s.mono, 1e-9)
                delta = tuple(max(0, a - b) for a, b
                              in zip(h["counts"], s.prev_serve[1]))
                n = sum(delta)
                window["qps"] = round(n / dt, 3)
                if n:
                    window["p50"] = quantile_from_counts(
                        h["bounds"], delta, 0.50)
                    window["p99"] = quantile_from_counts(
                        h["bounds"], delta, 0.99)
                window["events"] = n
            s.prev_serve = (h["bounds"], h["counts"])
        q = parsed["counters"].get(self.QUERIES_COUNTER)
        if q is not None:
            cur = {labels: v for labels, v in q.items()}
            if s.prev_queries is not None and s.mono is not None:
                total = err = shed = 0.0
                for labels, v in cur.items():
                    d = max(0.0, v - s.prev_queries.get(labels, 0.0))
                    total += d
                    status = dict(labels).get("status", "")
                    if status in _SHED_STATUSES:
                        shed += d
                    elif status != "ok":
                        err += d
                if total > 0:
                    window["errorFraction"] = round(err / total, 6)
                    window["shedRate"] = round(shed / total, 6)
                    window.setdefault("events", int(total))
            s.prev_queries = cur
        if window:
            s.window = window

    # -- views -----------------------------------------------------------
    def _fresh_locked(self, now: float) -> dict[str, _ReplicaSample]:
        return {name: s for name, s in self._samples.items()
                if s.parsed is not None and s.mono is not None
                and (now - s.mono) <= self.stale_after_s}

    def _refresh_meta_gauges(self) -> None:
        now = self._now()
        with self._lock:
            for name, s in self._samples.items():
                if s.mono is not None:
                    _G_SCRAPE_AGE.set(round(now - s.mono, 3), replica=name)
            _G_FRESH.set(len(self._fresh_locked(now)))

    def _on_drop(self, family: str) -> None:
        self._dropped_families.add(family)
        _C_MERGE_DROPPED.inc(family=family)

    def outliers(self) -> dict[str, list[str]]:
        """{replica: [signal, ...]} — windowed signal beyond the band
        around the fleet median. Needs >= 2 fresh replicas with enough
        window traffic; refreshes ``pio_fleet_outlier`` gauges."""
        now = self._now()
        with self._lock:
            fresh = self._fresh_locked(now)
            windows = {name: dict(s.window) for name, s in fresh.items()
                       if s.window.get("events", 0) >= self.min_window_events}
        flags: dict[str, list[str]] = {}
        for signal, floor in _SIGNAL_FLOORS.items():
            vals = {name: w[signal] for name, w in windows.items()
                    if signal in w}
            if len(vals) < 2:
                continue
            median = statistics.median(vals.values())
            cut = median * (1.0 + self.outlier_band) + floor
            for name, v in vals.items():
                if v > cut:
                    flags.setdefault(name, []).append(signal)
        live_keys = {(name, signal)
                     for name, signals in flags.items()
                     for signal in signals}
        with self._lock:
            for key in self._outlier_keys - live_keys:
                _G_OUTLIER.set(0.0, replica=key[0], signal=key[1])
            for key in live_keys:
                _G_OUTLIER.set(1.0, replica=key[0], signal=key[1])
            self._outlier_keys = live_keys
        return flags

    def fleet_slo(self, exclude: str | None = None) -> dict:
        """Merged SLO summary over fresh replicas (exact: raw good/bad
        counts summed, burn recomputed — see obs/slo.py). ``exclude``
        drops one replica — the drain policy asks "is the fleet WITHOUT
        this replica healthy?"."""
        now = self._now()
        with self._lock:
            fresh = self._fresh_locked(now)
            summaries = [s.stats.get("slo") for name, s in fresh.items()
                         if name != exclude
                         and isinstance(s.stats.get("slo"), dict)]
        return merge_slo_summaries(summaries)

    def fleet_burn(self, exclude: str | None = None) -> float | None:
        """Max fast-window burn across merged objectives; None when no
        fresh replica has reported an SLO block yet."""
        merged = self.fleet_slo(exclude=exclude)
        if not merged.get("replicas"):
            return None
        burns = [o.get("windows", {}).get("5m", {}).get("burnRate", 0.0)
                 for o in merged.get("objectives", [])]
        return max(burns) if burns else 0.0

    def replica_view(self) -> dict:
        """Per-replica scrape state + windowed signals, every entry
        stamped with ``ageSeconds`` (staleness is visible, not silent)."""
        now = self._now()
        with self._lock:
            out = {}
            for name, s in sorted(self._samples.items()):
                age = (round(now - s.mono, 3)
                       if s.mono is not None else None)
                out[name] = {
                    "ageSeconds": age,
                    "stale": (age is None or age > self.stale_after_s),
                    "scrapes": s.scrapes,
                    "failures": s.failures,
                    "lastError": s.last_error,
                    "window": dict(s.window),
                    "flightDumps": s.flight_dumps,
                }
            return out

    def stats_json(self) -> dict:
        """The ``/fleet/stats.json`` body: merged snapshot + per-replica
        windows + outliers + merged SLO + collector health."""
        now = self._now()
        with self._lock:
            fresh = self._fresh_locked(now)
            parsed = {name: s.parsed for name, s in fresh.items()}
        merged = fleet_snapshot(parsed, on_drop=self._on_drop)
        return {
            "merged": merged,
            "replicas": self.replica_view(),
            "outliers": self.outliers(),
            "slo": self.fleet_slo(),
            "collector": {
                "freshReplicas": len(fresh),
                "staleAfterSeconds": self.stale_after_s,
                "outlierBand": self.outlier_band,
                "droppedFamilies": sorted(self._dropped_families),
            },
        }

    def render_prometheus(self) -> str:
        """``/fleet/metrics``: Prometheus exposition of every fresh
        replica's counters and gauges with a ``replica`` label appended,
        the fleet-merged histograms (buckets + exact quantiles), and the
        collector's own meta families."""
        now = self._now()
        with self._lock:
            fresh = self._fresh_locked(now)
            parsed = {name: s.parsed for name, s in sorted(fresh.items())}
        lines: list[str] = []
        for kind_key, kind in (("counters", "counter"), ("gauges", "gauge")):
            families: dict[str, list[str]] = {}
            helps: dict[str, str] = {}
            for rep, p in parsed.items():
                for name, series in p[kind_key].items():
                    fam = families.setdefault(name, [])
                    helps.setdefault(name, p["help"].get(name, ""))
                    for labels, v in sorted(series.items()):
                        label_str = _fmt_labels(
                            tuple(n for n, _ in labels),
                            tuple(val for _, val in labels),
                            extra=(("replica", rep),))
                        fam.append(f"{name}{label_str} {_fmt_value(v)}")
            for name in sorted(families):
                if helps.get(name):
                    lines.append(f"# HELP {name} {helps[name]}")
                lines.append(f"# TYPE {name} {kind}")
                lines.extend(families[name])
        merged_h = merge_histograms(
            {rep: p["histograms"] for rep, p in parsed.items()},
            on_drop=self._on_drop)
        for name in sorted(merged_h):
            m = merged_h[name]
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for b, c in zip(m["bounds"], m["counts"]):
                cum += c
                lines.append(
                    f'{name}_bucket{{le="{_fmt_value(b)}"}} {cum}')
            lines.append(f'{name}_bucket{{le="+Inf"}} {m["count"]}')
            lines.append(f"{name}_sum {_fmt_value(float(m['sum']))}")
            lines.append(f"{name}_count {m['count']}")
            qn = f"{name}_summary"
            lines.append(f"# TYPE {qn} summary")
            for q, lbl in ((0.50, "0.5"), (0.95, "0.95"), (0.99, "0.99")):
                v = quantile_from_counts(m["bounds"], m["counts"], q)
                lines.append(f'{qn}{{quantile="{lbl}"}} '
                             f"{_fmt_value(float(v))}")
            lines.append(f"{qn}_sum {_fmt_value(float(m['sum']))}")
            lines.append(f"{qn}_count {m['count']}")
        self._refresh_meta_gauges()
        self.outliers()
        for fam in (_G_SCRAPE_AGE, _G_FRESH, _G_OUTLIER,
                    _C_SCRAPE_FAILURES, _C_MERGE_DROPPED):
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"
