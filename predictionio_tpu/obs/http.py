"""``GET /metrics`` — Prometheus text exposition for aiohttp apps.

Mounted on the event server (:7070), the engine server (:8000) and the
dashboard (:9000) so every plane is scrapeable with the same handler.
aiohttp is imported lazily: the registry itself must stay importable in
processes that never serve HTTP (train workers, the CLI).
"""

from __future__ import annotations

from .metrics import METRICS

__all__ = ["handle_metrics", "CONTENT_TYPE"]

#: Prometheus text exposition v0.0.4 content type
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


async def handle_metrics(request):
    from aiohttp import web

    return web.Response(
        text=METRICS.render_prometheus(),
        headers={"Content-Type": CONTENT_TYPE},
    )
