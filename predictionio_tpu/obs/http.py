"""``GET /metrics`` — Prometheus text exposition for aiohttp apps.

Mounted on the event server (:7070), the engine server (:8000) and the
dashboard (:9000) so every plane is scrapeable with the same handler.
aiohttp is imported lazily: the registry itself must stay importable in
processes that never serve HTTP (train workers, the CLI).
"""

from __future__ import annotations

from .metrics import METRICS
from .trace import TRACE_HEADER, ensure_request_id

__all__ = ["handle_metrics", "make_trace_middleware", "CONTENT_TYPE"]

#: Prometheus text exposition v0.0.4 content type
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


async def handle_metrics(request):
    from aiohttp import web

    return web.Response(
        text=METRICS.render_prometheus(),
        headers={"Content-Type": CONTENT_TYPE},
    )


def make_trace_middleware():
    """aiohttp middleware that adopts/mints the request id at ingress and
    stamps ``X-PIO-Request-ID`` on EVERY response — including paths that
    bail before any handler bookkeeping runs (admission-shed 429s,
    journal-full 503s, auth 401s, webhook errors). ``setdefault`` keeps
    handler-set stamps authoritative."""
    from aiohttp import web

    @web.middleware
    async def trace_middleware(request, handler):
        rid = ensure_request_id(request.headers.get(TRACE_HEADER))
        try:
            resp = await handler(request)
        except web.HTTPException as exc:
            exc.headers.setdefault(TRACE_HEADER, rid)
            raise
        resp.headers.setdefault(TRACE_HEADER, rid)
        return resp

    return trace_middleware
