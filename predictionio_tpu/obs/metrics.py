"""Process-wide metrics registry: counters, gauges, latency histograms.

Design constraints (ISSUE 5 tentpole):

- **No dependencies.** Pure stdlib — the prometheus_client package is not
  in the image, so the text exposition is rendered here.
- **O(1) record.** Histograms use fixed log-spaced bucket boundaries
  computed once; ``record()`` is a ``math.log`` + two adds under a lock,
  never a sort or a sample reservoir.
- **Thread + asyncio safe.** Every mutation holds a plain
  ``threading.Lock``; asyncio callers never await inside the registry so
  a sync lock cannot deadlock the loop, and worker threads (journal
  fsync, micro-batch dispatch, train supervisor heartbeat) share the
  same counters safely.
- **Snapshot quantiles.** ``Histogram.snapshot()`` yields count/sum/
  p50/p95/p99 estimated by linear interpolation inside the bucket that
  crosses the target rank — the same estimate Prometheus's
  ``histogram_quantile`` would compute from the exported buckets.

The module-global ``METRICS`` registry is the process's single telemetry
sink; subsystems hold metric handles created at import time and
``METRICS.reset()`` zeroes values in place (handles stay valid) so tests
can isolate without re-importing the world.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable

__all__ = [
    "METRICS",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "DEFAULT_TIME_BUCKETS_S",
    "quantile_from_counts",
]

#: Log-spaced latency boundaries in seconds: 0.1 ms doubling up to
#: ~3.5 min, 22 finite buckets + overflow. Covers a 64 us device call and
#: a 120 s hung drain with the same fixed table.
_BUCKET_MIN_S = 1e-4
_BUCKET_FACTOR = 2.0
_BUCKET_COUNT = 22
DEFAULT_TIME_BUCKETS_S: tuple[float, ...] = tuple(
    _BUCKET_MIN_S * _BUCKET_FACTOR ** i for i in range(_BUCKET_COUNT)
)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...],
                extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def quantile_from_counts(bounds: tuple[float, ...],
                         counts: Iterable[int], q: float) -> float:
    """Interpolated quantile over raw (non-cumulative) bucket counts,
    ``counts[-1]`` being the overflow bucket. This is THE quantile
    function of the system: ``Histogram`` delegates to it and the fleet
    aggregator (obs/aggregate.py) calls it on merged bucket counts, so a
    merged fleet quantile is bitwise-equal to the quantile a single
    histogram fed the union of samples would report — exactness by
    construction, not by approximation."""
    counts = list(counts)
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        lo = 0.0 if i == 0 else bounds[i - 1]
        if i >= len(bounds):
            return bounds[-1]  # overflow: report top boundary
        hi = bounds[i]
        if cum + c >= rank:
            frac = (rank - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return bounds[-1]


class _Metric:
    """Base: one metric family, optionally with label dimensions. Child
    time series are keyed by their label-value tuple; the unlabeled
    family uses the empty tuple."""

    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.labelnames = labelnames
        self._lock = threading.Lock()
        self._series: dict[tuple[str, ...], float] = {}

    def labels(self, **kv: str) -> "_Child":
        if tuple(sorted(kv)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(kv)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        return _Child(self, key)

    def _reset(self) -> None:
        with self._lock:
            self._series = {k: 0.0 for k in self._series}

    # -- accessors ---------------------------------------------------
    def value(self, *label_values: str) -> float:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            return self._series.get(key, 0.0)

    def series(self) -> dict[tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._series.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        for key, v in items:
            lines.append(
                f"{self.name}{_fmt_labels(self.labelnames, key)} "
                f"{_fmt_value(v)}")
        return lines


class _Child:
    """One labeled time series of a Counter/Gauge family."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: _Metric, key: tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, n: float = 1.0) -> None:
        m = self._metric
        with m._lock:
            m._series[self._key] = m._series.get(self._key, 0.0) + n

    def set(self, v: float) -> None:
        m = self._metric
        with m._lock:
            m._series[self._key] = float(v)

    @property
    def value(self) -> float:
        m = self._metric
        with m._lock:
            return m._series.get(self._key, 0.0)


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1.0, **kv: str) -> None:
        key = (tuple(str(kv[n_]) for n_ in self.labelnames) if kv else ())
        if kv and len(kv) != len(self.labelnames):
            raise ValueError(f"{self.name}: labels {self.labelnames} required")
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + n


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float, **kv: str) -> None:
        key = (tuple(str(kv[n_]) for n_ in self.labelnames) if kv else ())
        with self._lock:
            self._series[key] = float(v)


class Histogram:
    """Log-bucketed latency histogram (unlabeled; one family = one site).

    ``record(v)`` is O(1): the bucket index is
    ``ceil(log(v/min)/log(factor))`` clamped into the fixed table, so a
    0 or negative observation lands in bucket 0 and anything above the
    top boundary lands in the overflow (``+Inf``) bucket. Quantiles
    interpolate linearly within the crossing bucket; an overflow-bucket
    quantile reports the top finite boundary (the histogram cannot see
    further).
    """

    kind = "histogram"

    def __init__(self, name: str, help_: str,
                 buckets: Iterable[float] = DEFAULT_TIME_BUCKETS_S):
        self.name = name
        self.help = help_
        self.bounds: tuple[float, ...] = tuple(sorted(buckets))
        if not self.bounds or any(b <= 0 for b in self.bounds):
            raise ValueError("histogram bucket bounds must be positive")
        self._log_min = math.log(self.bounds[0])
        self._log_factor = (
            math.log(self.bounds[1] / self.bounds[0])
            if len(self.bounds) > 1 else 1.0)
        self._lock = threading.Lock()
        # counts[i] observations <= bounds[i]; counts[-1] is overflow
        self._counts = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0

    def _index(self, v: float) -> int:
        if v <= self.bounds[0]:
            return 0
        if v > self.bounds[-1]:
            return len(self.bounds)  # overflow
        # O(1) for the log-spaced default table; falls back to a scan
        # only when the computed slot disagrees (custom bucket tables)
        i = int(math.ceil((math.log(v) - self._log_min)
                          / self._log_factor - 1e-9))
        i = min(max(i, 0), len(self.bounds) - 1)
        if self.bounds[i] >= v and (i == 0 or self.bounds[i - 1] < v):
            return i
        for j, b in enumerate(self.bounds):
            if v <= b:
                return j
        return len(self.bounds)

    def record(self, v: float) -> None:
        i = self._index(v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v

    def _quantile_locked(self, q: float) -> float:
        return quantile_from_counts(self.bounds, self._counts, q)

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._quantile_locked(q)

    def bucket_counts(self) -> tuple[tuple[int, ...], int, float]:
        """Cumulative-free raw bucket counts ``(counts, count, sum)``
        (``counts[-1]`` is the overflow bucket). Samplers that need a
        *windowed* quantile — e.g. the admission controller's recent
        queue-wait p99 — diff two of these and interpolate over the
        delta instead of the lifetime distribution."""
        with self._lock:
            return tuple(self._counts), self._count, self._sum

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._count = 0
            self._sum = 0.0

    def render(self) -> list[str]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            q50 = self._quantile_locked(0.50)
            q95 = self._quantile_locked(0.95)
            q99 = self._quantile_locked(0.99)
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        cum = 0
        for b, c in zip(self.bounds, counts):
            cum += c
            lines.append(
                f'{self.name}_bucket{{le="{_fmt_value(b)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{self.name}_sum {_fmt_value(float(s))}")
        lines.append(f"{self.name}_count {total}")
        # precomputed quantiles as a sibling summary family, so scrapers
        # without histogram_quantile (and humans with curl) read p99
        # straight off the page
        qn = f"{self.name}_summary"
        lines.append(f"# HELP {qn} precomputed quantiles of {self.name}")
        lines.append(f"# TYPE {qn} summary")
        lines.append(f'{qn}{{quantile="0.5"}} {_fmt_value(float(q50))}')
        lines.append(f'{qn}{{quantile="0.95"}} {_fmt_value(float(q95))}')
        lines.append(f'{qn}{{quantile="0.99"}} {_fmt_value(float(q99))}')
        lines.append(f"{qn}_sum {_fmt_value(float(s))}")
        lines.append(f"{qn}_count {total}")
        return lines


class MetricsRegistry:
    """All metric families of one process, keyed by family name.

    Re-registering an existing name with the same kind returns the
    existing family (modules may be re-imported in tests); a kind clash
    is a programming error and raises.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _register(self, cls, name: str, help_: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{type(m).__name__}")
                return m
            m = cls(name, help_, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "",
                labelnames: tuple[str, ...] = ()) -> Counter:
        return self._register(Counter, name, help_, labelnames=labelnames)

    def gauge(self, name: str, help_: str = "",
              labelnames: tuple[str, ...] = ()) -> Gauge:
        return self._register(Gauge, name, help_, labelnames=labelnames)

    def histogram(self, name: str, help_: str = "",
                  buckets: Iterable[float] = DEFAULT_TIME_BUCKETS_S,
                  ) -> Histogram:
        return self._register(Histogram, name, help_, buckets=buckets)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Zero every value in place; handles held by subsystems stay
        valid. Used by the test suite between tests."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()

    def snapshot(self) -> dict:
        """JSON-friendly view: {counters: {...}, gauges: {...},
        histograms: {name: {count,sum,p50,p95,p99}}}. Labeled series
        key as ``name{label="v"}``."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in metrics:
            if isinstance(m, Histogram):
                out["histograms"][name] = m.snapshot()
                continue
            dest = out["counters"] if isinstance(m, Counter) else out["gauges"]
            for key, v in sorted(m.series().items()):
                label = _fmt_labels(m.labelnames, key)
                dest[f"{name}{label}"] = v
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition v0.0.4 of every family, ending in
        the required trailing newline."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: list[str] = []
        for _, m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


#: the process-wide registry every subsystem instruments through
METRICS = MetricsRegistry()
