"""Device ledger: per-executable XLA cost/memory accounting (ISSUE 12).

Every compile that lands in the shared ``ExecutableCache`` (fused top-k
kernels, XLA fallback programs, sharded retrievers, the ANN scorer, the
ALS fold-in solver) is analyzed here: ``cost_analysis()`` flops/bytes and
``memory_analysis()`` argument/output/temp sizes become a ledger entry,
so at any moment the ledger answers "how much HBM does this deployment
hold and in what?" — the accounting substrate the multi-engine A/B and
device-resident pipeline arcs need before N variants can share a device
pool (ALX, arXiv:2112.02194, attributes step time and memory per shard;
Google's ads-serving paper, arXiv:2501.10546, treats compile/memory
telemetry as a precondition for co-locating models).

Graceful degradation is a hard contract: cpu jaxlib builds may lack one
or both analyses (or return them in a different shape), so every probe
runs under ``try/except`` and a failed probe just flags the entry
``analysisUnavailable`` — telemetry must NEVER take down serving or
training. Accounting invariant (pinned by test_device_telemetry):
``pio_hbm_bytes{component}`` equals the sum of resident ledger entry
bytes per component; evicting a cache entry decrements the gauge by
exactly the entry's bytes.
"""

from __future__ import annotations

import dataclasses
import threading

from .metrics import METRICS

#: executable kinds = ExecutableCache key namespaces (key[0]). "other"
#: absorbs future namespaces without a registry change — compile
#: histograms are per-kind FAMILIES (the registry's histograms are
#: unlabeled), pre-registered from this fixed tuple so the doc-catalog
#: guard sees every concrete name at import time.
KINDS = ("kernel", "xla", "sharded", "ann", "fold_in", "pipeline", "other")

COMPILE_HISTOGRAMS = {
    k: METRICS.histogram(
        f"pio_xla_compile_{k}_seconds",
        f"wall time of one {k} executable build (trace+lower+compile) "
        "admitted to the ExecutableCache")
    for k in KINDS
}

_G_HBM = METRICS.gauge(
    "pio_hbm_bytes",
    "bytes resident on device per component, from each executable's "
    "memory_analysis (argument+output+temp+code) or tracked buffer "
    "sizes; decremented on cache evict",
    labelnames=("component",))

_G_HBM_WATERMARK = METRICS.gauge(
    "pio_hbm_watermark_bytes",
    "high-water mark of the summed pio_hbm_bytes ledger total since "
    "process start (or last reset)")

#: dispatch-level padding waste: (b_pad - b_orig) / b_pad per retrieval
#: dispatch. Ratio buckets, not time buckets; record() clamps values
#: <= bounds[0] into bucket 0, so a 0.0 (full bucket) observation is
#: well-defined.
_M_PADDING_WASTE = METRICS.histogram(
    "pio_dispatch_padding_waste_ratio",
    "fraction of each dispatched batch that is padding: "
    "(padded_batch - real_batch) / padded_batch",
    buckets=(1 / 64, 1 / 32, 1 / 16, 1 / 8, 1 / 4, 3 / 8, 1 / 2,
             5 / 8, 3 / 4, 7 / 8, 1.0))

_M_ANALYSIS_UNAVAILABLE = METRICS.counter(
    "pio_xla_analysis_unavailable_total",
    "executables whose cost/memory analysis probe failed (cpu jaxlib "
    "or incompatible executable shape) — flagged, never fatal")


@dataclasses.dataclass
class LedgerEntry:
    """One executable's accounting record. ``bytes`` fields come from
    ``memory_analysis``; flops/cost_bytes from ``cost_analysis``;
    either may be unavailable (``analysis_unavailable``)."""
    key: tuple
    kind: str
    compile_seconds: float = 0.0
    flops: float = 0.0
    cost_bytes: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    analysis_unavailable: bool = False

    @property
    def total_bytes(self) -> int:
        return (self.argument_bytes + self.output_bytes
                + self.temp_bytes + self.generated_code_bytes)

    def describe(self) -> dict:
        return {
            "key": repr(self.key),
            "kind": self.kind,
            "compileSeconds": round(self.compile_seconds, 6),
            "flops": self.flops,
            "costBytes": self.cost_bytes,
            "argumentBytes": self.argument_bytes,
            "outputBytes": self.output_bytes,
            "tempBytes": self.temp_bytes,
            "generatedCodeBytes": self.generated_code_bytes,
            "totalBytes": self.total_bytes,
            "analysisUnavailable": self.analysis_unavailable,
        }


def _unwrap_executable(value):
    """Cache values are either a bare compiled executable or a
    ``(compiled, flag)`` tuple (the packing convention)."""
    if isinstance(value, tuple) and value:
        return value[0]
    return value


def _probe_cost(exe, entry: LedgerEntry) -> bool:
    """cost_analysis() → flops / bytes accessed. Returns False when the
    probe fails (entry untouched)."""
    try:
        cost = exe.cost_analysis()
        # some jaxlib versions wrap the per-computation dict in a list
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if not isinstance(cost, dict):
            return False
        entry.flops = float(cost.get("flops", 0.0))
        entry.cost_bytes = float(cost.get("bytes accessed", 0.0))
        return True
    except Exception:
        return False


def _probe_memory(exe, entry: LedgerEntry) -> bool:
    """memory_analysis() → argument/output/temp/code sizes. Returns
    False when the probe fails (entry untouched)."""
    try:
        mem = exe.memory_analysis()
        if mem is None:
            return False
        entry.argument_bytes = int(
            getattr(mem, "argument_size_in_bytes", 0) or 0)
        entry.output_bytes = int(
            getattr(mem, "output_size_in_bytes", 0) or 0)
        entry.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
        entry.generated_code_bytes = int(
            getattr(mem, "generated_code_size_in_bytes", 0) or 0)
        return True
    except Exception:
        return False


class DeviceLedger:
    """Process-wide accounting of device-resident executables/buffers.

    Two-phase protocol mirroring ExecutableCache.get_or_build's locking:
    ``analyze`` runs OUTSIDE the cache lock (the analysis probes can be
    arbitrarily slow), ``admit``/``discard`` run inside it (cheap dict +
    gauge ops), so the ledger's residency view and the cache's never
    diverge. Lock order is strictly cache → ledger; the ledger never
    calls back into a cache."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[tuple, LedgerEntry] = {}
        #: non-executable device buffers (e.g. the delta patch table),
        #: component -> bytes, set absolutely via track_buffer
        self._buffers: dict[str, int] = {}
        self._watermark = 0

    # -- compile accounting (ExecutableCache hook) --------------------

    def kind_of(self, key) -> str:
        k = key[0] if isinstance(key, tuple) and key else None
        return k if k in KINDS else "other"

    def analyze(self, key, value, compile_seconds: float) -> LedgerEntry:
        """Build a ledger entry for a freshly compiled cache value.
        Called OUTSIDE the cache lock. Never raises."""
        kind = self.kind_of(key)
        entry = LedgerEntry(key=key, kind=kind,
                            compile_seconds=float(compile_seconds))
        try:
            exe = _unwrap_executable(value)
            got_cost = _probe_cost(exe, entry)
            got_mem = _probe_memory(exe, entry)
            entry.analysis_unavailable = not (got_cost or got_mem)
        except Exception:
            entry.analysis_unavailable = True
        try:
            COMPILE_HISTOGRAMS[kind].record(entry.compile_seconds)
            if entry.analysis_unavailable:
                _M_ANALYSIS_UNAVAILABLE.inc()
        except Exception:
            pass
        return entry

    def admit(self, entry: LedgerEntry) -> None:
        """Record an entry as device-resident (call when its cache
        insert actually lands). Idempotent per key."""
        try:
            with self._lock:
                old = self._entries.get(entry.key)
                delta = entry.total_bytes - (old.total_bytes if old else 0)
                self._entries[entry.key] = entry
                self._bump_locked(entry.kind, delta)
        except Exception:
            pass

    def discard(self, key) -> None:
        """Drop a key's residency (cache evict). Unknown keys no-op."""
        try:
            with self._lock:
                entry = self._entries.pop(key, None)
                if entry is not None:
                    self._bump_locked(entry.kind, -entry.total_bytes)
        except Exception:
            pass

    def _bump_locked(self, component: str, delta: int) -> None:
        if delta:
            _G_HBM.labels(component=component).inc(delta)
        total = self._total_locked()
        if total > self._watermark:
            self._watermark = total
            _G_HBM_WATERMARK.set(float(total))

    def _total_locked(self) -> int:
        return (sum(e.total_bytes for e in self._entries.values())
                + sum(self._buffers.values()))

    # -- non-executable device buffers --------------------------------

    def track_buffer(self, component: str, nbytes: int) -> None:
        """Set a component's buffer residency ABSOLUTELY (the patch
        table is re-counted whole on every mutation — simpler and
        self-healing vs incremental deltas)."""
        try:
            with self._lock:
                old = self._buffers.get(component, 0)
                self._buffers[component] = int(nbytes)
                _G_HBM.set(float(nbytes), component=component)
                if int(nbytes) != old:
                    total = self._total_locked()
                    if total > self._watermark:
                        self._watermark = total
                        _G_HBM_WATERMARK.set(float(total))
        except Exception:
            pass

    # -- dispatch padding ----------------------------------------------

    def record_padding_waste(self, real: int, padded: int) -> None:
        """One retrieval dispatch padded ``real`` rows up to ``padded``.
        waste = (padded - real) / padded; a full bucket records 0.0."""
        try:
            if padded <= 0:
                return
            _M_PADDING_WASTE.record(max(0.0, (padded - real) / padded))
        except Exception:
            pass

    # -- views ---------------------------------------------------------

    def top_executables(self, n: int = 5) -> list[dict]:
        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: e.total_bytes, reverse=True)
        return [e.describe() for e in entries[:n]]

    def entry_keys(self) -> set:
        with self._lock:
            return set(self._entries)

    def incident_brief(self) -> dict:
        """Compact block for flight-recorder incident files: the HBM
        watermark + top-5 executables by bytes — enough to triage an
        OOM-adjacent incident from the dump alone."""
        with self._lock:
            watermark = self._watermark
            total = self._total_locked()
        return {
            "totalBytes": total,
            "watermarkBytes": watermark,
            "topExecutables": self.top_executables(5),
        }

    def snapshot(self) -> dict:
        with self._lock:
            comps: dict[str, dict] = {}
            for e in self._entries.values():
                c = comps.setdefault(e.kind, {
                    "bytes": 0, "entries": 0, "analysisUnavailable": False})
                c["bytes"] += e.total_bytes
                c["entries"] += 1
                c["analysisUnavailable"] |= e.analysis_unavailable
            for comp, nbytes in self._buffers.items():
                c = comps.setdefault(comp, {
                    "bytes": 0, "entries": 0, "analysisUnavailable": False})
                c["bytes"] += nbytes
            total = self._total_locked()
            watermark = self._watermark
            top = sorted(self._entries.values(),
                         key=lambda e: e.total_bytes, reverse=True)[:5]
        snap = {
            "components": comps,
            "totalBytes": total,
            "watermarkBytes": watermark,
            "topExecutables": [e.describe() for e in top],
            "paddingWaste": _M_PADDING_WASTE.snapshot(),
            "compile": {k: h.snapshot()
                        for k, h in COMPILE_HISTOGRAMS.items()
                        if h.snapshot()["count"]},
        }
        return snap

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._buffers.clear()
            self._watermark = 0


#: process-wide singleton, mirroring METRICS / FLIGHT
LEDGER = DeviceLedger()
