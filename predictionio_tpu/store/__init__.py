"""Event store facade (L2) — what engine templates read (reference:
data/src/main/scala/io/prediction/data/store/)."""

from .event_store import EventStore, app_name_to_id

__all__ = ["EventStore", "app_name_to_id"]
