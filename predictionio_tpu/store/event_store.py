"""Event store facade — the app-*name*-based API engine templates call.

Analog of reference ``PEventStore``/``LEventStore`` (reference: data/src/
main/scala/io/prediction/data/store/PEventStore.scala:30-114,
LEventStore.scala:147-250): resolves app name -> (appId, channelId) via the
metadata store (store/Common.scala appNameToId) and delegates to the event
backend. One facade serves both roles; the "parallel" read returns a
columnar ``EventFrame`` ready for device sharding, the "local" reads
return iterators (used on the serving hot path, e.g. the ecommerce
template's seen-events filter).
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Iterator, Sequence


from ..storage import EventQuery, PropertyMap, Storage
from ..storage.event import Event
from ..storage.events_base import ANY, StorageError
from ..storage.frame import EventFrame

__all__ = ["EventStore", "app_name_to_id"]


def _validate_host_shard(index: int, count: int) -> None:
    # validate BEFORE any single-host shortcut: a misconfigured launch
    # (e.g. (3, 1)) must fail loudly, not silently ingest the full stream
    # on several processes at once
    if count < 1 or not (0 <= index < count):
        raise ValueError(
            f"host_shard ({index}, {count}) invalid: need count >= 1 and "
            f"0 <= index < count")


def app_name_to_id(app_name: str, channel_name: str | None = None) -> tuple[int, int | None]:
    """(reference: data/.../store/Common.scala:31-56)"""
    meta = Storage.get_metadata()
    app = meta.app_get_by_name(app_name)
    if app is None:
        raise StorageError(f"Invalid app name {app_name!r}")
    if channel_name is None:
        return app.id, None
    for ch in meta.channel_get_by_appid(app.id):
        if ch.name == channel_name:
            return app.id, ch.id
    raise StorageError(f"Invalid channel name {channel_name!r} for app {app_name!r}")


class EventStore:
    """Facade bound (optionally) to a default app/channel from the Context."""

    def __init__(self, default_app_name: str | None = None,
                 default_channel_name: str | None = None):
        self._default_app = default_app_name
        self._default_channel = default_channel_name

    def _resolve(self, app_name: str | None, channel_name: str | None) -> tuple[int, int | None]:
        app = app_name or self._default_app
        if app is None:
            raise StorageError("no app name given and Context has no app binding")
        return app_name_to_id(app, channel_name or self._default_channel)

    # -- parallel reads (PEventStore.scala:54-114) -------------------------
    def find_frame(
        self,
        app_name: str | None = None,
        channel_name: str | None = None,
        *,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: Any = ANY,
        target_entity_id: Any = ANY,
        host_shard: tuple[int, int] | None = None,
    ) -> EventFrame:
        """Columnar scan for training (PEventStore.find analog).

        ``host_shard=(index, count)`` keeps only the entities hashing to
        this host's shard — the multi-host data-loading contract: each
        process of a ``jax.distributed`` job passes
        ``(process_index, process_count)`` and ingests a disjoint slice of
        the event stream with every entity's full history on one host
        (deterministic splitmix64 entity hash, the HBase row-key-prefix
        analog — storage/partition.py). Pass None on single-host.
        """
        app_id, channel_id = self._resolve(app_name, channel_name)
        query = EventQuery(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=tuple(event_names) if event_names else None,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )
        if host_shard is not None:
            index, count = host_shard
            _validate_host_shard(index, count)
            if count > 1:
                # stream-filter BEFORE materializing columns: per-host
                # peak memory is this host's slice (+ one hash chunk),
                # not the full dataset
                from ..storage.partition import iter_host_shard

                events = Storage.get_events().find(query)
                return EventFrame.from_events(
                    iter_host_shard(events, index, count))
        return Storage.get_events().find_frame(query)

    def aggregate_properties(
        self,
        app_name: str | None = None,
        entity_type: str = "",
        channel_name: str | None = None,
        *,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        required: Sequence[str] | None = None,
    ) -> dict[str, PropertyMap]:
        """(PEventStore.aggregateProperties, PEventStore.scala:78-114)"""
        app_id, channel_id = self._resolve(app_name, channel_name)
        return Storage.get_events().aggregate_properties(
            app_id,
            entity_type=entity_type,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )

    # -- local reads (LEventStore.scala:46-250; the serving hot path) ------
    def find(
        self,
        app_name: str | None = None,
        channel_name: str | None = None,
        *,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: Any = ANY,
        target_entity_id: Any = ANY,
        limit: int | None = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        app_id, channel_id = self._resolve(app_name, channel_name)
        return Storage.get_events().find(
            EventQuery(
                app_id=app_id,
                channel_id=channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=tuple(event_names) if event_names else None,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                limit=limit,
                reversed=latest,
            )
        )

    def find_by_entity(
        self,
        entity_type: str,
        entity_id: str,
        app_name: str | None = None,
        **kwargs,
    ) -> Iterator[Event]:
        """(LEventStore.findByEntity, LEventStore.scala:46-100)"""
        return self.find(
            app_name=app_name, entity_type=entity_type, entity_id=entity_id, **kwargs
        )
