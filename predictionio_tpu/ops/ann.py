"""IVF-style approximate MIPS retrieval in pure JAX (the 100M-item path).

Brute-force top-k (ops/retrieval.py) scans every catalog row per query —
the right answer until the catalog outgrows what one scan per query can
afford. This module trades a bounded slice of recall for a sub-linear
scan, with the structure large-scale ads/recsys serving stacks use
(arXiv:2501.10546: quantize, prune, then exact-rescore the survivors):

1. **Partition** — k-means the item factors into ~sqrt(N) cells at
   deploy/reload time (jitted Lloyd iterations over a bounded training
   sample, then a chunked full-catalog assignment). Cell sizes are
   CAPPED at ``max_cell_factor`` x the mean: natural k-means cell sizes
   are heavily skewed, and the padded dense cell layout below pays for
   the LARGEST cell on every probe — overflow items spill to their
   next-nearest cell instead (bounded padding beats a point of recall;
   the spill fraction is small because items fill nearest-first).
2. **Quantize** — centroids are stored int8 (per-centroid scale) or
   bf16; the coarse [B, C] scoring pass runs over dequantized
   centroids, so cell selection is cheap and the full-precision item
   factors are only touched for cells that survive.
3. **Probe + rescore** — the top ``nprobe`` cells per query are
   gathered ([B, L, D] per probe step inside one ``lax.scan``) and
   exact-rescored in f32 (HIGHEST precision, matching the exact path's
   ranking), then one ``lax.top_k`` over the [B, nprobe*L] candidates.

Everything after build time is one compiled XLA program, AOT-warmed
through the shared ``ExecutableCache`` exactly like the exact
retrievers, and served through the same ``_dispatch_topk`` entry (same
padding/empty-catalog/packed-pull policy, same ``retrieval.topk`` chaos
site).

Escape hatches, all automatic:

- catalogs under ``min_items`` never build an index (``exact_fallback``
  — the scan is already fast there);
- a failed index build (chaos site ``retrieval.ann_build``) falls back
  to exact retrieval instead of failing the deploy;
- ``nprobe >= n_cells`` would scan everything anyway, so those queries
  DELEGATE to the exact compiled program — bit-for-bit equal to
  ``DeviceRetriever`` (the parity edge tests pin this), because a
  gathered-rescore matmul is NOT bitwise identical to the full
  dot_general even at HIGHEST precision.

The probe budget scales with the requested k (``effective_nprobe``):
a brownout-clamped k=10 query probes ~sqrt(10/64) of the configured
budget, so the PR-6 top-k clamp reduces rescore work, not just the
response length.
"""

from __future__ import annotations

import dataclasses
import math
import time

import numpy as np

from ..obs.metrics import METRICS
from ..obs.waterfall import mark_stage
from ..workflow.faults import FAULTS
from .retrieval import (EXEC_CACHE, PACKED_IDX_LIMIT, _RETRIEVER_TOKENS,
                        _dispatch_topk, _query_shapes, DeviceRetriever)

__all__ = ["AnnIndex", "AnnRetriever", "build_index", "pick_cells",
           "effective_nprobe", "kmeans_centroids", "DEFAULT_NPROBE",
           "ANN_MIN_ITEMS", "NPROBE_REF_K"]

#: Catalogs below this serve exact — the brute scan is already fast and
#: an index would spend build seconds to make recall worse than 1.0.
ANN_MIN_ITEMS = 16_384

#: The k at which the configured ``nprobe`` applies in full; smaller
#: requests probe ~sqrt(k / NPROBE_REF_K) of it (see effective_nprobe).
NPROBE_REF_K = 64

#: Default probe budget, calibrated on the committed bench's clustered
#: 262k catalog: effective ~26 at k=10 lands recall@10 ~0.96 at ~1.5x
#: the exact scan's throughput (docs/operations.md "Retrieval at scale").
DEFAULT_NPROBE = 52

# ISSUE 7 satellites: the index must be scrapeable — cells / probe
# budget / dtype / build cost / fallback state as pio_retrieval_*
# metrics (docs/operations.md metric catalog has one row each)
_M_CELLS = METRICS.gauge(
    "pio_retrieval_index_cells",
    "k-means cells in the active ANN index (0 = exact retrieval)")
_M_NPROBE = METRICS.gauge(
    "pio_retrieval_nprobe_effective",
    "effective probe budget of the most recent ANN query (k-scaled)")
_M_BUILD = METRICS.histogram(
    "pio_retrieval_index_build_seconds",
    "wall seconds building the ANN index (k-means + layout + quantize)")
_M_FALLBACK = METRICS.gauge(
    "pio_retrieval_exact_fallback",
    "1 when an ANN-configured retriever is serving exact instead "
    "(small catalog or failed index build)")
_M_DTYPE = METRICS.gauge(
    "pio_retrieval_index_dtype",
    "active ANN centroid quantization (1 on the active dtype's series)",
    labelnames=("dtype",))
_M_QUERIES = METRICS.counter(
    "pio_retrieval_queries_total",
    "retrieval calls by serving mode (ann / exact_delegate when "
    "nprobe covers every cell / exact_fallback)",
    labelnames=("mode",))


def pick_cells(n_total: int) -> int:
    """Default cell count: the power of two nearest sqrt(N) (coarse scan
    and per-probe rescore balance at ~sqrt(N) cells of ~sqrt(N) items),
    clamped to [32, 4096]."""
    if n_total <= 1:
        return 1
    return int(min(4096, max(32, 2 ** round(math.log2(math.sqrt(n_total))))))


def effective_nprobe(nprobe: int, k: int, n_cells: int, cell_len: int) -> int:
    """Probe budget for one query: ``nprobe`` scaled by sqrt(k /
    NPROBE_REF_K) — half the cells for a quarter of the k — capped at
    ``nprobe``, then floored so the probed rows can still hold k
    results. The floor OVERRIDES the cap: the compiled program calls
    ``top_k(candidates, k)`` and under-gathering is a shape error, not
    a recall loss (and the floor is always satisfiable because
    n_cells * cell_len >= n_total >= k_pad). When the floor reaches
    n_cells the caller's full-cover path delegates to exact.
    A full-cover budget (nprobe >= n_cells) is never reduced: it is the
    exact-parity contract, not a performance setting."""
    nprobe = max(1, min(int(nprobe), n_cells))
    if nprobe >= n_cells:
        return n_cells
    min_probe = max(1, math.ceil(k / max(1, cell_len)))
    if min_probe >= n_cells:
        return n_cells
    eff = math.ceil(nprobe * math.sqrt(max(1, k) / NPROBE_REF_K))
    return max(min(eff, nprobe), min_probe)


def kmeans_centroids(items: np.ndarray, n_cells: int, *, iters: int = 30,
                     sample: int = 262_144, seed: int = 0) -> np.ndarray:
    """Lloyd k-means over a bounded sample of the catalog; each
    iteration is ONE jitted program (argmin assignment + one-hot
    aggregation), so build time stays seconds at bench scale."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n = len(items)
    sample = max(int(sample), n_cells)
    tr = items if n <= sample else items[rng.choice(n, sample, replace=False)]
    cent = tr[rng.choice(len(tr), n_cells, replace=False)].astype(np.float32)

    @jax.jit
    def step(cent, x):
        d = (jnp.sum(x * x, 1)[:, None] - 2.0 * (x @ cent.T)
             + jnp.sum(cent * cent, 1)[None, :])
        a = jnp.argmin(d, 1)
        one = jax.nn.one_hot(a, cent.shape[0], dtype=x.dtype)
        cnt = one.sum(0)
        newc = (one.T @ x) / jnp.maximum(cnt, 1.0)[:, None]
        # an emptied centroid keeps its position instead of collapsing
        # to zero (it can re-acquire items in a later iteration)
        return jnp.where(cnt[:, None] > 0, newc, cent)

    xs = jnp.asarray(tr, jnp.float32)
    for _ in range(max(1, iters)):
        cent = step(cent, xs)
    return np.asarray(cent)


def _capped_labels(items: np.ndarray, cent: np.ndarray, cap: int,
                   fanout: int = 8) -> np.ndarray:
    """Nearest-centroid assignment with a hard per-cell capacity: every
    item ranks its ``fanout`` nearest centroids (chunked host matmuls),
    then items place nearest-first — an item whose best cell is full
    spills to its next-nearest with room. Caps the padded cell length
    the probe loop pays for at ``cap`` without re-clustering."""
    n, n_cells = len(items), len(cent)
    fanout = min(fanout, n_cells)
    cn = np.sum(cent * cent, axis=1)
    ranks = np.empty((n, fanout), np.int32)
    d1 = np.empty(n, np.float32)
    for i in range(0, n, 65_536):
        d2 = cn[None, :] - 2.0 * (items[i:i + 65_536] @ cent.T)
        part = np.argpartition(d2, fanout - 1, axis=1)[:, :fanout]
        pd = np.take_along_axis(d2, part, axis=1)
        o = np.argsort(pd, axis=1, kind="stable")
        ranks[i:i + 65_536] = np.take_along_axis(part, o, axis=1)
        d1[i:i + 65_536] = pd[np.arange(len(pd)), o[:, 0]]
    # Vectorized nearest-first placement: one pass per fanout rank, not
    # one Python iteration per item (O(N) interpreter loops are minutes
    # at 100M rows). Within a pass, items are grouped by candidate cell
    # (stable sort keeps the confident-first order inside each group)
    # and each group accepts up to its remaining capacity.
    labels = np.full(n, -1, np.int32)
    counts = np.zeros(n_cells, np.int64)
    remaining = np.argsort(d1, kind="stable")  # confident items first
    for r in range(fanout):
        if not len(remaining):
            break
        cand = ranks[remaining, r].astype(np.int64)
        o = np.argsort(cand, kind="stable")
        sc = cand[o]
        first = np.r_[True, sc[1:] != sc[:-1]]
        run_start = np.maximum.accumulate(
            np.where(first, np.arange(len(sc)), 0))
        pos = np.arange(len(sc)) - run_start  # rank within the cell group
        ok = pos < (cap - counts[sc])
        placed = np.zeros(len(remaining), bool)
        placed[o[ok]] = True
        labels[remaining[placed]] = cand[placed]
        counts += np.bincount(sc[ok], minlength=n_cells)
        remaining = remaining[~placed]
    if len(remaining):
        # every ranked cell full: pack the emptiest cells' free slots
        # (total capacity n_cells * cap >= n, so slots always suffice)
        free = np.maximum(cap - counts, 0)
        cell_order = np.argsort(counts, kind="stable")
        slots = np.repeat(cell_order, free[cell_order])[:len(remaining)]
        labels[remaining] = slots.astype(np.int32)
    return labels


@dataclasses.dataclass
class AnnIndex:
    """The built index: dense padded cells + quantized centroids.

    ``cells`` is [n_cells, cell_len, dim] f32 (cell-major reorder of the
    catalog; pad rows are zero), ``ids`` is [n_cells, cell_len] int32
    original row ids with -1 pads. ``centroids`` is int8 [C, D] with
    per-centroid ``scales`` [C, 1] f32 (or bf16 with unit scales)."""

    centroids: np.ndarray
    scales: np.ndarray
    cells: np.ndarray
    ids: np.ndarray
    n_total: int
    dim: int
    n_cells: int
    cell_len: int
    quantize: str
    build_seconds: float


def build_index(items: np.ndarray, *, n_cells: int | None = None,
                kmeans_iters: int = 30, kmeans_sample: int = 262_144,
                max_cell_factor: float = 2.0, quantize: str = "int8",
                seed: int = 0) -> AnnIndex:
    """Partition + quantize the catalog (the deploy/reload-time step)."""
    if quantize not in ("int8", "bf16"):
        raise ValueError(f"quantize must be 'int8' or 'bf16', got {quantize!r}")
    t0 = time.perf_counter()
    items = np.asarray(items, np.float32)
    n, d = items.shape
    n_cells = int(n_cells) if n_cells else pick_cells(n)
    n_cells = max(1, min(n_cells, n))
    cent = kmeans_centroids(items, n_cells, iters=kmeans_iters,
                            sample=kmeans_sample, seed=seed)
    cap = max(1, math.ceil(max(1.0, max_cell_factor) * n / n_cells))
    labels = _capped_labels(items, cent, cap)
    order = np.argsort(labels, kind="stable")
    counts = np.bincount(labels, minlength=n_cells)
    cell_len = int(max(8, ((counts.max() + 7) // 8) * 8))
    cells = np.zeros((n_cells, cell_len, d), np.float32)
    ids = np.full((n_cells, cell_len), -1, np.int32)
    start = 0
    for c in range(n_cells):
        cnt = int(counts[c])
        cells[c, :cnt] = items[order[start:start + cnt]]
        ids[c, :cnt] = order[start:start + cnt]
        start += cnt
    if quantize == "int8":
        scales = (np.max(np.abs(cent), axis=1, keepdims=True) / 127.0
                  ).astype(np.float32)
        scales = np.maximum(scales, 1e-12)
        cent_q = np.clip(np.round(cent / scales), -127, 127).astype(np.int8)
    else:  # bf16 storage, unit scales — same dequant program shape
        import ml_dtypes

        cent_q = cent.astype(ml_dtypes.bfloat16)
        scales = np.ones((n_cells, 1), np.float32)
    return AnnIndex(centroids=cent_q, scales=scales, cells=cells, ids=ids,
                    n_total=n, dim=d, n_cells=n_cells, cell_len=cell_len,
                    quantize=quantize,
                    build_seconds=time.perf_counter() - t0)


class AnnRetriever:
    """Serving-surface twin of ``DeviceRetriever`` (``topk`` /
    ``prewarm`` / ``n_total``) over an IVF index. Can always produce an
    exact compiled program too — the delegate for full-cover probes, the
    fallback for small catalogs and failed builds (so a deploy
    configured ``mode: ann`` can never be LESS available than exact).
    The delegate is built LAZILY on first use: the padded cells already
    cost up to ``max_cell_factor`` x the catalog in HBM, and a
    replicated full-precision copy on top of that is exactly what will
    not fit at the catalog sizes ANN exists for (the host f32 array is
    kept instead — RAM, not HBM)."""

    def __init__(self, items: np.ndarray, *, nprobe: int = DEFAULT_NPROBE,
                 quantize: str = "int8", n_cells: int | None = None,
                 min_items: int = ANN_MIN_ITEMS, kmeans_iters: int = 30,
                 kmeans_sample: int = 262_144, max_cell_factor: float = 2.0,
                 interpret=None, seed: int = 0):
        import jax
        import jax.numpy as jnp

        items = np.asarray(items, np.float32)
        self.n_total, self.dim = items.shape
        self.nprobe = max(1, int(nprobe))
        self.min_items = max(0, int(min_items))
        self.last_effective_nprobe: int | None = None
        self._token = next(_RETRIEVER_TOKENS)
        # the exact delegate/fallback is built lazily from this host
        # copy — only the full-cover / fallback / empty-k paths pay its
        # HBM, not every ANN deploy
        self._items = items
        self._interpret = interpret
        self._exact_cached: DeviceRetriever | None = None
        self.index: AnnIndex | None = None
        self.fallback_reason: str | None = None
        if self.n_total < max(self.min_items, 2):
            self.fallback_reason = "small_catalog"
        else:
            try:
                FAULTS.fire("retrieval.ann_build")  # chaos site: a failed
                # build must degrade to exact, never fail the deploy
                self.index = build_index(
                    items, n_cells=n_cells, kmeans_iters=kmeans_iters,
                    kmeans_sample=kmeans_sample,
                    max_cell_factor=max_cell_factor, quantize=quantize,
                    seed=seed)
            except Exception as e:  # noqa: BLE001 — availability first
                self.fallback_reason = f"build_failed: {e}"
        if self.index is not None:
            ix = self.index
            self._cent_dev = jax.device_put(jnp.asarray(ix.centroids))
            self._scales_dev = jax.device_put(jnp.asarray(ix.scales))
            self._cells_dev = jax.device_put(jnp.asarray(ix.cells))
            self._ids_dev = jax.device_put(jnp.asarray(ix.ids))
            _M_BUILD.record(ix.build_seconds)
            _M_CELLS.set(ix.n_cells)
            _M_DTYPE.set(0, dtype="int8")
            _M_DTYPE.set(0, dtype="bf16")
            _M_DTYPE.set(1, dtype=ix.quantize)
            _M_FALLBACK.set(0)
        else:
            _M_CELLS.set(0)
            _M_FALLBACK.set(1)

    @property
    def _exact(self) -> DeviceRetriever:
        if self._exact_cached is None:
            self._exact_cached = DeviceRetriever(self._items,
                                                 interpret=self._interpret)
        return self._exact_cached

    @property
    def lane_dim(self) -> int:
        """Query lane width the compiled ANN programs lower against
        (128-rounded feature dim; the programs slice ``q[:, :d]`` back
        out). Queries pre-padded to this width pass through
        ``_dispatch_topk``'s lane pad unchanged AND through the exact
        delegate bitwise-identically — the contract the device-resident
        pipeline's gather handoff (``ops/pipeline.py``) relies on: a
        gathered ``[b_pad, lane_dim]`` matrix needs no host re-pad and
        cannot perturb the delegate-vs-ann fallback numerics."""
        return ((self.dim + 127) // 128) * 128

    # -- compiled ANN program ---------------------------------------------
    def _build_call(self, b_pad: int, k_pad: int, eff: int, *,
                    pin: bool = False):
        key = ("ann", self._token, b_pad, k_pad, eff)
        call = EXEC_CACHE.get_or_build(
            key, lambda: self._compile(b_pad, k_pad, eff))
        if pin:
            EXEC_CACHE.pin(key)
        return call

    def _compile(self, b_pad: int, k_pad: int, eff: int):
        """AOT-compile one (batch, k, nprobe) ANN shape: coarse
        quantized-centroid scan -> top-eff probe -> scan-over-probes
        gather + batched f32 rescore -> masked top-k. Returns the packed
        [B, 2k] executable under the shared packing policy."""
        import jax
        import jax.numpy as jnp

        ix = self.index
        d, n_total = ix.dim, self.n_total
        packed = n_total < PACKED_IDX_LIMIT

        def run(q, cent, scales, cells, ids):
            q = q[:, :d]  # _dispatch_topk lane-pads queries to 128
            cent_f = cent.astype(jnp.float32) * scales
            coarse = jax.lax.dot_general(
                q, cent_f, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            _, probe = jax.lax.top_k(coarse, eff)
            # ascending probe order: the gathered candidate buffer is
            # cell-major like the exact scan, so ties resolve stably
            probe = jnp.sort(probe, axis=1)

            def body(carry, pj):  # pj: [B] — one probed cell per query
                g = cells[pj]           # [B, L, D] gather
                gi = ids[pj]            # [B, L]
                sc = jax.lax.dot_general(
                    q, g, (((1,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)  # rank-stable
                # against the exact path's f32 scores
                return carry, (sc, gi)

            _, (ss, ii) = jax.lax.scan(body, 0, probe.T)
            b = q.shape[0]
            ss = jnp.transpose(ss, (1, 0, 2)).reshape(b, -1)
            ii = jnp.transpose(ii, (1, 0, 2)).reshape(b, -1)
            ss = jnp.where(ii >= 0, ss, -jnp.inf)  # cell pads out
            vals, sel = jax.lax.top_k(ss, k_pad)
            idx = jnp.take_along_axis(ii, sel, axis=1)
            idx = jnp.where(jnp.isfinite(vals), idx, -1).astype(jnp.int32)
            if packed:
                return jnp.concatenate(
                    [vals, idx.astype(jnp.float32)], axis=1)
            return vals, idx

        d_pad = ((d + 127) // 128) * 128
        sds = jax.ShapeDtypeStruct
        compiled = jax.jit(run).lower(
            sds((b_pad, d_pad), jnp.float32),
            sds(ix.centroids.shape, ix.centroids.dtype),
            sds(ix.scales.shape, jnp.float32),
            sds(ix.cells.shape, jnp.float32),
            sds(ix.ids.shape, jnp.int32),
        ).compile()
        return compiled, packed

    # -- serving surface ---------------------------------------------------
    def topk(self, queries, k: int):
        """(values [B, k], indices [B, k]) — same contract as the exact
        retrievers (indices -1 beyond catalog / past the candidates the
        probed cells held)."""
        if self.index is None:
            _M_QUERIES.inc(mode="exact_fallback")
            return self._exact.topk(queries, k)
        q = np.asarray(queries, np.float32)
        b = 1 if q.ndim == 1 else q.shape[0]
        k_eff = min(k, self.n_total)
        if k_eff <= 0:
            return self._exact.topk(queries, k)  # empty-result contract
        _, k_pad = _query_shapes(b, k_eff, self.n_total)
        eff = effective_nprobe(self.nprobe, k_pad, self.index.n_cells,
                               self.index.cell_len)
        self.last_effective_nprobe = eff
        _M_NPROBE.set(eff)
        if eff >= self.index.n_cells:
            # full cover: every cell would be rescored — the exact
            # program IS that computation, bit-for-bit (the gathered
            # rescore is not bitwise identical to one full dot_general)
            _M_QUERIES.inc(mode="exact_delegate")
            return self._exact.topk(queries, k)
        _M_QUERIES.inc(mode="ann")
        # probe planning (nprobe calibration, cell cover) is host-side
        # assembly work in the stage waterfall; _dispatch_topk then
        # splits the invoke into dispatch/compute/scatter
        mark_stage("host_assembly")

        def invoke(qp, k_pad_):
            call, packed = self._build_call(qp.shape[0], k_pad_, eff)
            out = call(qp, self._cent_dev, self._scales_dev,
                       self._cells_dev, self._ids_dev)
            return out, packed

        return _dispatch_topk(q, self.n_total, k, invoke)

    def prewarm(self, batch_sizes=(1,), ks=(10,)) -> list[tuple[int, int]]:
        """AOT-build and PIN the hot (batch, k) ANN executables — same
        deploy-time contract as the exact retrievers; full-cover shapes
        warm the exact delegate instead."""
        warmed: list[tuple[int, int]] = []
        delegate_ks: list[int] = []
        for b in batch_sizes:
            for k in ks:
                k_eff = min(k, self.n_total)
                if b <= 0 or k_eff <= 0:
                    continue
                b_pad, k_pad = _query_shapes(b, k_eff, self.n_total)
                if (b_pad, k_pad) in warmed:
                    continue
                if self.index is None:
                    continue  # fallback: warmed via _exact below
                eff = effective_nprobe(self.nprobe, k_pad,
                                       self.index.n_cells,
                                       self.index.cell_len)
                if eff >= self.index.n_cells:
                    delegate_ks.append(k)
                    continue
                self._build_call(b_pad, k_pad, eff, pin=True)
                warmed.append((b_pad, k_pad))
        if self.index is None:
            warmed.extend(self._exact.prewarm(batch_sizes=batch_sizes, ks=ks))
        elif delegate_ks:
            warmed.extend(self._exact.prewarm(batch_sizes=batch_sizes,
                                              ks=tuple(delegate_ks)))
        return warmed

    def stats(self) -> dict:
        """Index/serving facts for /stats.json's ``retrieval`` block."""
        ix = self.index
        return {
            "mode": "exact_fallback" if ix is None else "ann",
            "exactFallback": ix is None,
            "fallbackReason": self.fallback_reason,
            "nTotal": self.n_total,
            "cells": ix.n_cells if ix else 0,
            "cellLen": ix.cell_len if ix else 0,
            "nprobe": self.nprobe,
            "lastEffectiveNprobe": self.last_effective_nprobe,
            "quantize": ix.quantize if ix else None,
            "indexBuildSeconds": round(ix.build_seconds, 3) if ix else None,
            "minItems": self.min_items,
        }
