"""Device-resident serving pipeline (ISSUE 16).

PR 11's stage waterfalls showed where a served request's time goes: of
the p50 66.6 ms batched request only ~1.3 ms was device compute — the
rest was Python host work around ``_dispatch_topk``: per-user ``dict``
lookups, a numpy gather of the query factor rows, fresh padding
allocations, and a host->device upload of the padded query matrix on
EVERY batch. This module removes that floor by making the query side of
serving device-resident, the way the item side already is
(``DeviceRetriever``):

* **Device-resident query table** — the model's user-factor matrix is
  uploaded ONCE into a capacity-padded ``[cap, D_pad]`` device buffer.
  The hot path ships only a tiny ``int32[b_pad]`` row-index vector; the
  compiled program gathers the factor rows on device. Row ``cap - 1``
  is a permanent zero sentinel: padding slots and unknown users gather
  it, which reproduces bit-for-bit the zero-row padding the legacy path
  builds with ``np.pad`` — the PR 13 bitwise replay gate holds across
  the rewrite.

* **Fused dispatch** — for an exact single-device retriever the gather
  composes with the SAME raw scoring program the legacy path compiles
  (``_raw_xla_call`` / the Pallas kernel), into one executable per
  (b_pad, k_pad) lattice point: rows -> gather -> dot -> top_k ->
  packed ``[b_pad, 2k]`` pull. For ANN / sharded retrievers the gather
  program materializes the query matrix on device and hands it to the
  retriever's own compiled programs, so their numerics (and their exact
  fallback policies) are untouched.

* **Double-buffered staging** — each b_pad lattice point owns two
  pinned int32 staging buffers. Batch N+1's host assembly fills one
  while batch N's device step holds the other; a third concurrent
  dispatch (or a hung swap — chaos site ``pipeline.swap``) falls back
  to a transient buffer, so a wedged handoff degrades through the
  micro-batcher's watchdog without poisoning the pinned pool. The
  BatchClock stage fence (obs/waterfall.py) marks host_assembly /
  device_dispatch / device_compute / result_scatter exactly like the
  legacy path, so the waterfall proves the overlap.

* **Buffer donation** — on backends with real buffer aliasing
  (tpu/gpu) the staging argument is donated (``donate_argnums``, the
  ALX pattern) so XLA reuses its allocation; on CPU donation is a
  no-op-with-warning, so it is gated off and
  ``pio_pipeline_donated_dispatch_total`` stays 0.

* **Copy-on-write refresh** — delta hot-patches (ISSUE 10) call
  ``refresh(new_table)``: the table is re-uploaded into a fresh device
  buffer of the SAME capacity and a clone sharing the compiled-program
  token is returned, so epoch bumps never invalidate compiled programs;
  in-flight dispatches keep the old table because it is an *argument*
  of the compiled call, not a captured constant. Only outgrowing the
  capacity headroom (rare) re-tokenizes and recompiles.

Deploy-time ``prewarm`` walks the full pad-bucketed (b_pad, k_pad)
lattice and accounts every pinned buffer in the PR 12 device ledger
(components ``pipeline_query_table`` / ``pipeline_staging``).
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from ..obs.device import LEDGER
from ..obs.metrics import METRICS
from ..obs.waterfall import mark_stage, stage_sink_active
from ..workflow.faults import FAULTS
from .retrieval import (
    EXEC_CACHE,
    PACKED_IDX_LIMIT,
    _query_shapes,
    _raw_call,
    _raw_xla_call,
    _RETRIEVER_TOKENS,
    DeviceRetriever,
)

log = logging.getLogger("pio.pipeline")

_M_OVERLAP = METRICS.gauge(
    "pio_pipeline_overlap_ratio",
    "fraction of pipelined dispatches whose host assembly overlapped "
    "another batch's in-flight device step (the double-buffer doing "
    "its job; ~0 under serial load, -> 1 under pipelined load)")
_M_STAGE_WAIT = METRICS.histogram(
    "pio_pipeline_staging_wait_seconds",
    "wait to acquire a pinned staging buffer for a pipelined dispatch "
    "(0 when one is free; bounded by the transient-fallback timeout)")
_M_DONATED = METRICS.counter(
    "pio_pipeline_donated_dispatch_total",
    "pipelined dispatches through a donating executable "
    "(donate_argnums engages on tpu/gpu backends only)")

#: How long a dispatch waits for a pinned staging buffer before falling
#: back to a transient allocation. Short on purpose: the fallback is
#: cheap (np.empty of a few hundred bytes) and a longer wait would let
#: a hung pipeline.swap handoff stall HEALTHY batches behind it.
STAGING_WAIT_S = 0.002

#: Pinned staging buffers per b_pad lattice point (the double buffer).
STAGING_DEPTH = 2


def _capacity(n_rows: int) -> int:
    """Query-table capacity for ``n_rows`` factor rows: ~12.5% headroom
    (plus the sentinel row) rounded up to a multiple of 256, so delta
    fold-ins append new users for a long time before a capacity growth
    forces a recompile. The ONE home of the policy — tests pin it."""
    need = n_rows + 1 + max(n_rows // 8, 63)
    return ((need + 255) // 256) * 256


class _SharedState:
    """Mutable pipeline state shared across copy-on-write ``refresh``
    clones: the staging pools, the overlap/dispatch counters, and the
    locks guarding them. Sharing by reference keeps the metrics and the
    double buffers continuous across delta epochs."""

    def __init__(self):
        self.cond = threading.Condition()
        self.staging: dict[int, list[np.ndarray]] = {}
        self.in_device = 0       # dispatches currently in their device step
        self.dispatches = 0
        self.overlapped = 0
        self.transient = 0       # dispatches that fell back off the pool


class ServingPipeline:
    """Device-resident query-side serving for one model's user factors.

    Built by ``RetrievalServingMixin.attach_pipeline`` over the model's
    attached retriever; ``topk_rows(rows, k)`` is the whole hot path:
    catalog-row indices in, (values, indices) out, zero per-request
    numpy factor math.
    """

    def __init__(self, query_table: np.ndarray, retriever, *,
                 _token: int | None = None, _capacity_rows: int | None = None):
        import jax
        import jax.numpy as jnp

        if retriever is None:
            raise ValueError("ServingPipeline requires an attached retriever")
        qt = np.asarray(query_table, np.float32)
        if qt.ndim != 2:
            raise ValueError("query table must be [rows, dim]")
        self._retriever = retriever
        self._fused = isinstance(retriever, DeviceRetriever)
        self.n_rows, self.dim = qt.shape
        self._cap = _capacity_rows or _capacity(self.n_rows)
        if self.n_rows + 1 > self._cap:
            self._cap = _capacity(self.n_rows)
        # lane width follows the retriever's own contract (lane_dim):
        # fused mode needs the padded item table's width for the dot;
        # gather mode needs whatever width makes the retriever's lane
        # pad a no-op. 128-rounding is only the fallback for retrievers
        # that predate the accessor.
        self._d_pad = int(getattr(retriever, "lane_dim", 0)) or (
            ((self.dim + 127) // 128) * 128)
        if self._d_pad < self.dim:
            raise ValueError("retriever lane width narrower than factors")
        self._token = _token if _token is not None else next(_RETRIEVER_TOKENS)
        self._sentinel = self._cap - 1  # permanently a zero row
        tab = np.zeros((self._cap, self._d_pad), np.float32)
        tab[: self.n_rows, : self.dim] = qt
        self._qtab = jax.device_put(jnp.asarray(tab))
        self._donate = jax.default_backend() in ("tpu", "gpu")
        self._state = _SharedState()
        LEDGER.track_buffer("pipeline_query_table", int(self._qtab.nbytes))

    # -- compiled programs --------------------------------------------

    def _exec_fused(self, b_pad: int, k_pad: int, *, pin: bool = False):
        """(compiled, is_packed) for rows -> gather -> score -> top_k.
        Composes the SAME raw scoring program the legacy path compiles,
        so a gathered batch scores bit-for-bit like a host-assembled
        one (the parity tests pin this)."""
        r = self._retriever
        n_total = r.n_total
        key = ("pipeline", self._token, "fused", b_pad, k_pad, self._cap,
               self._d_pad, int(r._items.shape[0]), n_total, self._donate)

        def build():
            import jax
            import jax.numpy as jnp

            if r._mode == "xla":
                raw = _raw_xla_call(n_total, k_pad)
            else:
                raw = _raw_call(b_pad, self._d_pad, int(r._items.shape[0]),
                                n_total, k_pad, r._tile_n,
                                r._mode == "interpret")
            packed = n_total < PACKED_IDX_LIMIT

            def fn(rows, qtab, items):
                vals, idx = raw(qtab[rows], items)
                if not packed:
                    return vals, idx
                return jnp.concatenate(
                    [vals, idx.astype(jnp.float32)], axis=1)

            jitted = (jax.jit(fn, donate_argnums=(0,)) if self._donate
                      else jax.jit(fn))
            compiled = jitted.lower(
                jax.ShapeDtypeStruct((b_pad,), jnp.int32),
                jax.ShapeDtypeStruct((self._cap, self._d_pad), jnp.float32),
                jax.ShapeDtypeStruct(r._items.shape, jnp.float32),
            ).compile()
            return compiled, packed

        out = EXEC_CACHE.get_or_build(key, build)
        if pin:
            EXEC_CACHE.pin(key)
        return out

    def _exec_gather(self, b_pad: int, *, pin: bool = False):
        """Compiled rows -> [b_pad, D_pad] device gather (the front end
        for retrievers with their own scoring programs: ANN, sharded)."""
        key = ("pipeline", self._token, "gather", b_pad, self._cap,
               self._d_pad, self._donate)

        def build():
            import jax
            import jax.numpy as jnp

            def fn(rows, qtab):
                return qtab[rows]

            jitted = (jax.jit(fn, donate_argnums=(0,)) if self._donate
                      else jax.jit(fn))
            return jitted.lower(
                jax.ShapeDtypeStruct((b_pad,), jnp.int32),
                jax.ShapeDtypeStruct((self._cap, self._d_pad), jnp.float32),
            ).compile()

        out = EXEC_CACHE.get_or_build(key, build)
        if pin:
            EXEC_CACHE.pin(key)
        return out

    # -- staging double buffer ----------------------------------------

    def _acquire_staging(self, b_pad: int) -> tuple[np.ndarray, bool]:
        """A staging buffer for one dispatch: a pinned one when the pool
        has a free slot (waiting at most STAGING_WAIT_S for the double
        buffer to swap), else a transient allocation — slow, but a hung
        handoff can never wedge the pool. Returns (buffer, transient)."""
        st = self._state
        t0 = time.perf_counter()
        with st.cond:
            pool = st.staging.get(b_pad)
            if pool is None:
                pool = st.staging[b_pad] = [
                    np.empty(b_pad, np.int32) for _ in range(STAGING_DEPTH)]
            if not pool:
                st.cond.wait(timeout=STAGING_WAIT_S)
            buf = pool.pop() if pool else None
        _M_STAGE_WAIT.record(time.perf_counter() - t0)
        if buf is None:
            with st.cond:
                st.transient += 1
            return np.empty(b_pad, np.int32), True
        return buf, False

    def _release_staging(self, b_pad: int, buf: np.ndarray,
                         transient: bool) -> None:
        if transient:
            return
        st = self._state
        with st.cond:
            st.staging.setdefault(b_pad, []).append(buf)
            st.cond.notify()

    def _fill_staging(self, buf: np.ndarray, rows: np.ndarray) -> None:
        """Host assembly: row ids into the staging buffer, out-of-table
        ids (unknown users, padding slots) redirected to the zero
        sentinel — the device-side equivalent of the legacy zero-pad."""
        b = rows.shape[0]
        np.copyto(buf[:b], np.where(
            (rows >= 0) & (rows < self.n_rows), rows, self._sentinel))
        buf[b:] = self._sentinel

    # -- hot path ------------------------------------------------------

    def topk_rows(self, rows, k: int):
        """(values [b, k_eff], indices [b, k_eff]) for a batch of
        catalog-row indices (int32; negatives score as unknown). The
        pipelined replacement for gather-pad-upload-score: the only
        per-request host work is filling an int32 staging buffer."""
        rows = np.asarray(rows, np.int32)
        b = rows.shape[0]
        n_total = self._retriever.n_total
        k_eff = min(k, n_total)
        if b == 0 or k_eff <= 0 or n_total == 0:
            return (np.zeros((b, 0), np.float32), np.zeros((b, 0), np.int32))
        b_pad, k_pad = _query_shapes(b, k_eff, n_total)
        LEDGER.record_padding_waste(b, b_pad)
        st = self._state
        buf, transient = self._acquire_staging(b_pad)
        try:
            with st.cond:
                overlapped = st.in_device > 0
            self._fill_staging(buf, rows)
            # the filled buffer is handed to the device step: the
            # double-buffer swap point (chaos site; a hang here holds
            # ONE pinned buffer and the watchdog 504s the batch)
            FAULTS.fire("pipeline.swap")
            if self._fused:
                out = self._dispatch_fused(buf, b, b_pad, k_eff, k_pad)
            else:
                out = self._dispatch_gather(buf, b, b_pad, k)
            with st.cond:
                st.dispatches += 1
                st.overlapped += 1 if overlapped else 0
                ratio = st.overlapped / st.dispatches
            _M_OVERLAP.set(ratio)
            return out
        finally:
            self._release_staging(b_pad, buf, transient)

    def _dispatch_fused(self, buf, b, b_pad, k_eff, k_pad):
        import jax

        attributing = stage_sink_active()
        if attributing:
            mark_stage("host_assembly")
        call, is_packed = self._exec_fused(b_pad, k_pad)
        st = self._state
        with st.cond:
            st.in_device += 1
        try:
            out = call(buf, self._qtab, self._retriever._items)
            if self._donate:
                _M_DONATED.inc()
            if attributing:
                mark_stage("device_dispatch")
            jax.block_until_ready(out)
            if attributing:
                mark_stage("device_compute")
        finally:
            with st.cond:
                st.in_device -= 1
        if is_packed:
            host = np.asarray(out)  # packed: ONE pull
            vals = host[:b, :k_eff]
            idx = host[:b, k_pad:k_pad + k_eff].astype(np.int32)
        else:
            vals, idx = out
            vals = np.asarray(vals)[:b, :k_eff]
            idx = np.asarray(idx)[:b, :k_eff]
        if attributing:
            mark_stage("result_scatter")
        return vals, idx

    def _dispatch_gather(self, buf, b, b_pad, k):
        """ANN / sharded: gather the query matrix on device, pull it,
        and hand it to the retriever's own compiled programs. The
        gathered rows are bit-identical to the host gather the legacy
        path does, so the retriever's numerics (and its exact-fallback
        policy) are untouched."""
        import jax

        call = self._exec_gather(b_pad)
        st = self._state
        with st.cond:
            st.in_device += 1
        try:
            qdev = call(buf, self._qtab)
            if self._donate:
                _M_DONATED.inc()
            jax.block_until_ready(qdev)
        finally:
            with st.cond:
                st.in_device -= 1
        # the retriever's _dispatch_topk re-fences the stage waterfall
        # and re-pads lanes (a no-op: the gather already padded them)
        return self._retriever.topk(np.asarray(qdev)[:b], k)

    # -- lifecycle -----------------------------------------------------

    def prewarm(self, batch_sizes=(1,), ks=(10,)) -> list[tuple]:
        """AOT-build and PIN this pipeline's executables for the full
        pad-bucketed lattice, allocate the pinned staging pairs, and
        account every pinned buffer in the device ledger. Returns the
        distinct cache keys warmed (digested into exec_cache_key)."""
        warmed: list[tuple] = []
        seen: set[tuple[int, int]] = set()
        gathered: set[int] = set()
        n_total = self._retriever.n_total
        for b in batch_sizes:
            for k in ks:
                k_eff = min(k, n_total)
                if b <= 0 or k_eff <= 0:
                    continue
                b_pad, k_pad = _query_shapes(b, k_eff, n_total)
                if (b_pad, k_pad) in seen:
                    continue
                seen.add((b_pad, k_pad))
                if self._fused:
                    self._exec_fused(b_pad, k_pad, pin=True)
                    warmed.append(("pipeline", "fused", b_pad, k_pad))
                elif b_pad not in gathered:
                    # the gather program is k-independent: one per b_pad
                    gathered.add(b_pad)
                    self._exec_gather(b_pad, pin=True)
                    warmed.append(("pipeline", "gather", b_pad))
                with self._state.cond:
                    self._state.staging.setdefault(b_pad, [
                        np.empty(b_pad, np.int32)
                        for _ in range(STAGING_DEPTH)])
        self._account_buffers()
        return warmed

    def _account_buffers(self) -> None:
        with self._state.cond:
            staged = sum(STAGING_DEPTH * b_pad * 4
                         for b_pad in self._state.staging)
        LEDGER.track_buffer("pipeline_staging", staged)
        LEDGER.track_buffer("pipeline_query_table", int(self._qtab.nbytes))

    def refresh(self, query_table: np.ndarray) -> "ServingPipeline":
        """Copy-on-write table swap for a delta epoch bump: re-upload
        ``query_table`` at the SAME capacity and return a clone sharing
        the compiled-program token, staging pools and counters — no
        compiled program is invalidated, and in-flight dispatches keep
        the old table (it is an argument, not a captured constant).
        Outgrowing the capacity headroom rebuilds from scratch (new
        token; the rare recompile is the documented cost of growth)."""
        import jax
        import jax.numpy as jnp

        qt = np.asarray(query_table, np.float32)
        if qt.ndim != 2 or qt.shape[1] != self.dim:
            raise ValueError("refresh requires a [rows, %d] table" % self.dim)
        if qt.shape[0] + 1 > self._cap:
            log.info("pipeline query table outgrew capacity %d -> "
                     "rebuilding (recompile)", self._cap)
            return ServingPipeline(qt, self._retriever)
        new = object.__new__(ServingPipeline)
        new.__dict__.update(self.__dict__)
        tab = np.zeros((self._cap, self._d_pad), np.float32)
        tab[: qt.shape[0], : self.dim] = qt
        new._qtab = jax.device_put(jnp.asarray(tab))
        new.n_rows = qt.shape[0]
        LEDGER.track_buffer("pipeline_query_table", int(new._qtab.nbytes))
        return new

    def stats(self) -> dict:
        st = self._state
        with st.cond:
            staged = {int(b): len(p) for b, p in st.staging.items()}
            return {
                "mode": "fused" if self._fused else "gather",
                "rows": self.n_rows,
                "capacity": self._cap,
                "dispatches": st.dispatches,
                "overlapRatio": (st.overlapped / st.dispatches
                                 if st.dispatches else 0.0),
                "transientStaging": st.transient,
                "stagingFree": staged,
                "donation": self._donate,
            }
