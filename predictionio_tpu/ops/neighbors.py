"""Host-side layout: COO ratings -> fixed-shape padded neighbor blocks.

The TPU ALS solver needs, for every user (resp. item), the list of rated
items (resp. rating users) as FIXED-SHAPE arrays — XLA cannot tile
variable-degree lists onto the MXU. This module builds that layout:

  ``NeighborBlocks``: ids [NB, B, D], vals [NB, B, D], mask [NB, B, D]

where B is the per-block row count (sharded over the mesh's data axis) and
D the padded max degree. This is the role MLlib ALS's
``InLinkBlock/OutLinkBlock`` shuffle layout plays in the reference's
training path (examples/.../ALSAlgorithm.scala -> org.apache.spark.mllib.
recommendation.ALS), re-thought for static shapes instead of shuffles:
layout is computed once on host with numpy sorts, then stays resident.

``build_bilinear_layout`` is the production entry: BOTH sides (user rows
gathering item factors and vice versa) built together in a PERMUTED
"slot" order, so that per-tier solved factors concatenate straight into
the factor arrays — measured on v5e, a TPU scatter runs at ~3-12M
rows/s (per-row overhead bound) versus ~470M rows/s for gathers, so the
design removes every scatter from the training step rather than trying
to speed one up.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from .. import native

__all__ = [
    "NeighborBlocks", "SideLayout", "TierMeta", "build_bilinear_layout",
    "build_neighbor_blocks", "geometric_tiers", "optimal_tiers",
]

_splitmix64 = native.splitmix64_np


@dataclasses.dataclass
class NeighborBlocks:
    """Padded per-row neighbor lists, reshaped into blocks.

    Validity is encoded in ``vals``: padded slots are exactly 0; genuine
    zero values are nudged to 1e-30 at build time, so consumers derive
    the mask as ``vals != 0`` instead of carrying a third array (a third
    of the layout's memory and transfer at 20M-rating scale). ``mask`` is
    computed lazily for the few callers (tests) that want it explicitly.
    """

    ids: np.ndarray  # int32 [NB, B, D] neighbor indices (0 where padded)
    vals: np.ndarray  # float32 [NB, B, D] ratings/confidences (0 where padded)
    num_rows: int  # true number of rows (before padding to NB*B)
    max_degree: int  # D after capping
    dropped: int  # entries dropped by the degree cap

    @property
    def mask(self) -> np.ndarray:  # float32 [NB, B, D] 1.0 = real entry
        return (self.vals != 0).astype(np.float32)

    @property
    def padded_rows(self) -> int:
        return self.ids.shape[0] * self.ids.shape[1]


@dataclasses.dataclass
class TierMeta:
    """Static facts the solver needs about one tier bucket."""

    span: int  # rows this tier contributes to the permuted factor array
    #: None for regular tiers (block row j IS slot offset+j). For a
    #: chunked tier: int32 [NB*B] mapping each block row (a chunk of a
    #: heavy row) to its owner's local slot 0..span-1, SORTED ascending —
    #: the solver segment-sums partial normal equations over it. Block
    #: padding rows map to the last local slot (their contribution is
    #: exactly zero, and a trailing index keeps the sequence sorted).
    seg: np.ndarray | None = None


@dataclasses.dataclass
class SideLayout:
    """One side of the permuted two-sided layout (see
    ``build_bilinear_layout``). The permuted factor array has ``slots``
    rows: tier spans back to back, then degree-0 rows, then ≥1 always-
    zero slot (``zero_slot`` = slots-1). ``pos[r]`` is true row r's slot.
    Block ``ids`` reference the OTHER side's slots; padded entries point
    at the other side's ``zero_slot``, so gathers return exact zeros and
    the solver needs no [B, D, R]-shaped validity mask."""

    buckets: list[NeighborBlocks]
    metas: list[TierMeta]
    slots: int
    pos: np.ndarray  # int32 [num_rows] true row -> slot
    zero_slot: int

    @property
    def dropped(self) -> int:
        return sum(b.dropped for b in self.buckets)


def geometric_tiers(max_degree: int, *, base: int = 16,
                    ratio: float = 1.25) -> tuple[int, ...]:
    """Degree-tier edges in (rough) geometric progression, each a multiple
    of 8, ending exactly at ``max_degree`` rounded up to 8.

    Padding waste per row is bounded by the ratio between consecutive
    tiers (worst case a row's degree is one past the previous edge).
    Padded entries cost real gather bandwidth (the per-row-bound TPU
    gather is the training step's floor), so the ratio is set fine
    (~14% average padding); tiers are cheap — every tier's normal
    equations concatenate into ONE batched solve (models/als._solve_side)
    and small tiers merge upward anyway (``merge_budget``).
    """
    top = max(8, ((max_degree + 7) // 8) * 8)
    edges: list[int] = []
    d = float(base)
    while d < top:
        e = int(((int(d) + 7) // 8) * 8)
        if not edges or e > edges[-1]:
            edges.append(e)
        d *= ratio
    if not edges or edges[-1] < top:
        edges.append(top)
    else:
        edges[-1] = top
    return tuple(edges)


def optimal_tiers(degrees: np.ndarray, *, tier_cost: int) -> tuple[int, ...]:
    """Degree-histogram-OPTIMAL tier edges: minimize
    Σ (rows in tier) x (tier edge)  +  tier_cost x (number of tiers)
    by dynamic programming over the distinct 8-rounded degrees present.
    Geometric edges bound worst-case padding by the ratio but ignore the
    actual distribution; on ML-20M's Poisson-bulk user degrees the DP
    places edges through the bulk and cuts padded gather rows ~2x for the
    same tier count. ``tier_cost`` is the padded-element equivalent of
    one extra tier dispatch (the merge_budget calibration)."""
    d8 = ((np.asarray(degrees, np.int64) + 7) // 8) * 8
    vals, rows = np.unique(d8[d8 > 0], return_counts=True)
    if len(vals) == 0:
        return (8,)
    csum = np.concatenate([[0], np.cumsum(rows)])
    n = len(vals)
    best = np.full(n + 1, np.inf)
    best[0] = 0.0
    choice = np.zeros(n + 1, np.int64)
    for i in range(1, n + 1):
        # one tier covering distinct degrees j..i-1, padded to vals[i-1]
        costs = best[:i] + (csum[i] - csum[:i]) * vals[i - 1] + tier_cost
        j = int(np.argmin(costs))
        best[i] = costs[j]
        choice[i] = j
    edges = []
    i = n
    while i > 0:
        edges.append(int(vals[i - 1]))
        i = choice[i]
    return tuple(reversed(edges))


def _assign_tiers(vcounts: np.ndarray, tiers, merge_budget: int,
                  eligible: np.ndarray, dp_cost: int) -> list[tuple[int, np.ndarray]]:
    """Group eligible rows into degree tiers. ``tiers="auto"`` computes
    histogram-optimal edges (``optimal_tiers`` — already cost-aware, no
    further merging); an explicit tuple is honored with small tiers
    merged upward when all their rows padded at the NEXT tier's width
    stay within ``merge_budget`` elements."""
    vmax = int(vcounts[eligible].max()) if eligible.any() else 0
    if tiers == "auto":
        tiers = optimal_tiers(vcounts[eligible], tier_cost=dp_cost)
        merge_budget = 0  # the DP already priced tier count
    elif vmax > tiers[-1]:
        # extend rather than drop: one extra tier holding the heaviest rows
        tiers = tuple(tiers) + (((vmax + 7) // 8) * 8,)
    out: list[tuple[int, np.ndarray]] = []
    pending: list[np.ndarray] = []
    pending_n = 0
    prev = 0
    for t_idx, tier_d in enumerate(tiers):
        last = t_idx == len(tiers) - 1
        sel = eligible & (vcounts > prev) & ((vcounts <= tier_d) | last)
        prev = tier_d
        row_idx = np.nonzero(sel)[0]
        cand_n = pending_n + len(row_idx)
        if cand_n == 0:
            continue
        if not last and cand_n * tiers[t_idx + 1] <= merge_budget:
            pending.append(row_idx)
            pending_n = cand_n
            continue
        if pending:
            row_idx = np.concatenate(pending + [row_idx])
            pending, pending_n = [], 0
        out.append((tier_d, row_idx))
    return out


@dataclasses.dataclass
class _ChunkClass:
    """Heavy rows whose balanced chunks share one padded width."""

    width: int
    owners: np.ndarray  # ascending row ids
    k: np.ndarray  # chunks per owner
    span: int


@dataclasses.dataclass
class _SidePlan:
    """One side's slot plan: where every row's factor lives in permuted
    order, before any blocks are built (both sides' plans must exist
    before either side's blocks, because ids hold the OTHER side's
    slots)."""

    tiers: list[tuple[int, np.ndarray]]  # (tier_d, original row ids)
    tier_block_rows: list[int]
    chunks: list[_ChunkClass]
    slots: int
    pos: np.ndarray  # int32 [num_rows]
    zero_slot: int


def _plan_side(counts: np.ndarray, *, tiers, gather_budget: int,
               chunk_cap: int | None, merge_budget, nnz: int,
               align: int = 8) -> _SidePlan:
    num_rows = len(counts)
    align = 8 * max(1, align) // math.gcd(8, max(1, align))  # lcm(8, align)
    if merge_budget == "auto":
        # balance point measured on v5e: one extra tier costs ~1.5ms of
        # dispatch, one padded entry ~4ns of gather+gramian — so merging
        # is worth up to ~400k extra padded elements per tier removed
        merge_budget = max(8192, nnz // 48)
    # the DP prices a tier at the marginal lax.map launch (~0.5ms), much
    # cheaper than the merge heuristic's bound — on ML-20M this choice
    # cuts total padding from ~32% to ~10% at ~18 tiers/side
    dp_cost = max(8192, nnz // 160)
    cap = 0
    heavy = np.zeros(num_rows, bool)
    if chunk_cap is not None:
        cap = max(8, (int(chunk_cap) // 8) * 8)
        heavy = counts > cap
    light = (counts > 0) & ~heavy
    tier_list = _assign_tiers(counts, tiers, merge_budget, light, dp_cost)

    pos = np.full(num_rows, -1, np.int64)
    off = 0
    tier_block_rows = []
    for tier_d, row_idx in tier_list:
        br = _block_rows_for(tier_d, gather_budget, len(row_idx))
        span = max(1, math.ceil(len(row_idx) / br)) * br
        pos[row_idx] = off + np.arange(len(row_idx))
        tier_block_rows.append(br)
        off += span

    chunks: list[_ChunkClass] = []
    if heavy.any():
        heavy_rows = np.nonzero(heavy)[0]  # ascending
        k = -(-counts[heavy_rows] // cap)  # balanced chunk counts
        # balanced chunks of a degree-d row are ceil(d/k) wide, i.e. in
        # (cap/2, cap]; group heavy rows into histogram-optimal width
        # classes so a near-half-full chunk doesn't pad all the way to
        # cap. Each row contributes k chunks, so the DP weights widths
        # by repetition (padding cost = k x class edge per row).
        width = ((-(-counts[heavy_rows] // k) + 7) // 8) * 8
        edges = optimal_tiers(np.repeat(width, k), tier_cost=dp_cost)
        cls = np.searchsorted(np.asarray(edges), width, side="left")
        for c in np.unique(cls):
            sel = cls == c
            owners = heavy_rows[sel]
            span = ((len(owners) + 7) // 8) * 8
            pos[owners] = off + np.arange(len(owners))
            chunks.append(_ChunkClass(width=int(edges[c]), owners=owners,
                                      k=k[sel], span=span))
            off += span

    deg0 = np.nonzero(counts == 0)[0]
    pos[deg0] = off + np.arange(len(deg0))
    off += len(deg0)
    # ≥1 guaranteed-zero slot, rounded so factor rows shard evenly over a
    # model axis of size `align` (tensor-parallel NamedSharding requires
    # dim 0 divisible by the axis size)
    slots = -(-(off + 1) // align) * align
    return _SidePlan(
        tiers=tier_list, tier_block_rows=tier_block_rows, chunks=chunks,
        slots=slots, pos=pos.astype(np.int32), zero_slot=slots - 1,
    )


def _stable_argsort_bounded(keys: np.ndarray, key_max: int) -> np.ndarray:
    """np.argsort(kind="stable") for non-negative bounded int keys, via
    the native parallel counting sort when available (bit-identical —
    test_native pins it). The entry-stream sorts are the layout build's
    dominant host cost at 100M-rating scale."""
    out = native.counting_argsort(keys, key_max)
    if out is not None:
        return out
    return np.argsort(keys, kind="stable")


def _build_side(plan: _SidePlan, rows, cols_slots, vals, *, zero_other: int,
                gather_budget: int, seed: int) -> SideLayout:
    """Build one side's blocks from its plan. ``cols_slots`` is the
    neighbor column array ALREADY remapped to the other side's slots.

    One radix sort groups the entry stream by tier, then every tier works
    on a contiguous slice — the naive per-tier full-stream mask costs
    O(nnz · tiers) (measured 8s at ML-20M scale against this path's ~2s).
    """
    num_rows = len(plan.pos)
    rows = np.asarray(rows)
    if rows.dtype.itemsize > 4:
        rows = rows.astype(np.int32)  # numpy radix-sorts small ints
    vals = np.asarray(vals)
    buckets: list[NeighborBlocks] = []
    metas: list[TierMeta] = []

    # tier code per entry: 1..T = regular tier, 0 = chunked classes
    # (int32 so the native counting sort takes it without a 100M-entry
    # cast copy)
    n_tiers = len(plan.tiers)
    tier_of_row = np.zeros(num_rows, np.int32)
    for t, (_tier_d, row_idx) in enumerate(plan.tiers):
        tier_of_row[row_idx] = t + 1
    tcode = tier_of_row[rows]
    order_t = _stable_argsort_bounded(tcode, n_tiers + 1)
    # tier boundaries from the histogram — searchsorted with sorter=
    # walks the permutation indirection and measured ~6 s at 100M entries
    bounds = np.zeros(n_tiers + 2, np.int64)
    np.cumsum(np.bincount(tcode, minlength=n_tiers + 1), out=bounds[1:])

    remap = np.empty(num_rows, np.int64)
    for t, ((tier_d, row_idx), br) in enumerate(
            zip(plan.tiers, plan.tier_block_rows)):
        sl = order_t[bounds[t + 1]:bounds[t + 2]]
        remap[row_idx] = np.arange(len(row_idx))
        b = build_neighbor_blocks(
            remap[rows[sl]], cols_slots[sl], vals[sl],
            len(row_idx), block_rows=br, degree_cap=tier_d,
            pad_id=zero_other, seed=seed,
        )
        buckets.append(b)
        metas.append(TierMeta(span=b.padded_rows))

    if plan.chunks:
        hv = order_t[bounds[0]:bounds[1]]  # all chunked-class entries
        rows_h, cols_h, vals_h = rows[hv], cols_slots[hv], vals[hv]
        counts = np.bincount(rows_h, minlength=num_rows)
        order = _stable_argsort_bounded(rows_h, num_rows - 1)
        starts = np.zeros(num_rows + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        rs = rows_h[order]
        pos_in = np.arange(len(rows_h), dtype=np.int64) - starts[rs]
        cols_o, vals_o = cols_h[order], vals_h[order]
        k_full = np.zeros(num_rows, np.int64)
        hv_base = np.full(num_rows, -1, np.int64)
        for cc in plan.chunks:
            k_full[cc.owners] = cc.k
            hv_base[cc.owners] = np.concatenate([[0], np.cumsum(cc.k[:-1])])
            sel = hv_base[rs] >= 0
            # balanced chunk of each entry: position p of d entries split
            # into k chunks lands in chunk p*k//d (sizes differ by at most
            # 1, so every chunk fits this width class)
            vrow = (hv_base[rs[sel]]
                    + (pos_in[sel] * k_full[rs[sel]]) // counts[rs[sel]])
            n_hv = int(cc.k.sum())
            br = _block_rows_for(cc.width, gather_budget, n_hv)
            b = build_neighbor_blocks(
                vrow, cols_o[sel], vals_o[sel], n_hv, block_rows=br,
                degree_cap=cc.width, pad_id=zero_other, seed=seed,
            )
            # seg: block row (chunk) -> owner's local slot, sorted
            # ascending; block padding rows map to the LAST local slot
            # (their partial equations are exactly zero, and a trailing
            # index keeps the sequence sorted for segment_sum's fast path)
            seg = np.full(b.padded_rows, cc.span - 1, np.int32)
            seg[:n_hv] = np.repeat(
                np.arange(len(cc.owners), dtype=np.int32), cc.k)
            buckets.append(b)
            metas.append(TierMeta(span=cc.span, seg=seg))
            k_full[cc.owners] = 0
            hv_base[cc.owners] = -1

    return SideLayout(buckets=buckets, metas=metas, slots=plan.slots,
                      pos=plan.pos, zero_slot=plan.zero_slot)


def build_bilinear_layout(
    u_idx: np.ndarray,
    i_idx: np.ndarray,
    vals: np.ndarray,
    num_users: int,
    num_items: int,
    *,
    tiers: tuple[int, ...] | str = "auto",
    gather_budget: int = 2_000_000,
    seed: int = 0,
    chunk_cap: int | None = 2048,
    merge_budget: int | str = "auto",
    align: int = 8,
) -> tuple[SideLayout, SideLayout]:
    """Both sides of the ALS layout, ALX-style density-grouped and
    PERMUTED so the training step needs zero scatters:

    - rows are grouped by degree tier (``tiers="auto"`` computes
      histogram-OPTIMAL edges via ``optimal_tiers`` — zero entries
      dropped, total padding + per-tier dispatch cost minimized by DP
      over the observed degree distribution; explicit tuples auto-extend
      past their last edge and merge small tiers within ``merge_budget``,
      lossless either way), block row counts sized so one block's
      gathered factors stay within ``gather_budget`` elements;
    - rows heavier than ``chunk_cap`` split into balanced chunks riding a
      dedicated cap-wide tier, their partial normal equations segment-
      summed per owner (kills the one-block-per-80k-degree-row tail);
    - factor arrays live in tier-concatenation order during training
      (``SideLayout.pos`` maps true rows to slots), padded slots point at
      the other side's guaranteed-zero slot; ``align`` rounds each side's
      slot count so factor rows shard evenly over a model axis of that
      size (pass the mesh's model-axis size for tensor-parallel factors).

    Replaces the factor-block shuffle MLlib ALS performs every iteration
    (reference examples/.../ALSAlgorithm.scala:96-154): layout is computed
    once on host, then stays device-resident for every iteration.
    """
    u_idx = np.asarray(u_idx, np.int64)
    i_idx = np.asarray(i_idx, np.int64)
    nnz = len(u_idx)
    counts_u = np.bincount(u_idx, minlength=num_users) if nnz else np.zeros(num_users, np.int64)
    counts_i = np.bincount(i_idx, minlength=num_items) if nnz else np.zeros(num_items, np.int64)
    kw = dict(tiers=tiers, gather_budget=gather_budget, chunk_cap=chunk_cap,
              merge_budget=merge_budget, nnz=nnz, align=align)
    plan_u = _plan_side(counts_u, **kw)
    plan_i = _plan_side(counts_i, **kw)
    lay_u = _build_side(plan_u, u_idx, plan_i.pos[i_idx], vals,
                        zero_other=plan_i.zero_slot,
                        gather_budget=gather_budget, seed=seed)
    lay_i = _build_side(plan_i, i_idx, plan_u.pos[u_idx], vals,
                        zero_other=plan_u.zero_slot,
                        gather_budget=gather_budget, seed=seed)
    return lay_u, lay_i


def _block_rows_for(tier_d: int, gather_budget: int, n_rows: int) -> int:
    """Per-block row count for a tier: bounded by the gather budget
    (B*D elements of peak gathered factors) and BALANCED across the
    tier's blocks — a tier one row past a block boundary must not pad a
    whole extra block of rows (ceil-divide the rows over the block count
    the budget implies; waste < 8 rows per block)."""
    b_max = min(8192, max(8, gather_budget // max(tier_d, 8)))
    nb = max(1, math.ceil(max(n_rows, 1) / b_max))
    return max(8, ((math.ceil(n_rows / nb) + 7) // 8) * 8) if n_rows else 8


def build_neighbor_blocks(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    *,
    block_rows: int = 4096,
    max_degree: int | None = None,
    degree_cap: int = 1024,
    seed: int = 0,
    pad_id: int = 0,
) -> NeighborBlocks:
    """Group (rows, cols, vals) COO triples by row into padded blocks.

    - D = min(max observed degree, ``degree_cap``) rounded up to a multiple
      of 8 (float32 sublane tiling).
    - Rows with degree > D keep a deterministic hash-keyed subsample (the
      same trade MLlib users make with sampling heavy users); the key is
      splitmix64(seed, row, pos) so the native C++ path and the numpy
      fallback produce identical layouts.
    - Rows padded to a multiple of ``block_rows``.
    - Padded id slots hold ``pad_id`` (the permuted layout points them at
      the other side's guaranteed-zero factor slot so consumers skip the
      [B, D, R]-wide validity mask; the default 0 keeps the standalone
      mask-deriving path working).

    Dispatches to the C++ counting-sort kernel (predictionio_tpu/native)
    when built; falls back to numpy sorts otherwise.
    """
    # Exact-zero values are nudged to a tiny epsilon so that downstream
    # consumers may derive the validity mask as ``vals != 0`` (the padded
    # slots are exactly 0) instead of carrying a separate mask array —
    # that mask is a third of the layout's device traffic at 20M-rating
    # scale. 1e-30 contributes nothing at float32/bfloat16 precision.
    vals = np.asarray(vals, np.float32)
    if len(vals) and (vals == 0).any():
        vals = np.where(vals == 0, np.float32(1e-30), vals)

    if len(rows) == 0:
        d = 8
        nb = max(1, math.ceil(max(num_rows, 1) / block_rows))
        shape = (nb, block_rows, d)
        return NeighborBlocks(
            ids=np.full(shape, pad_id, np.int32),
            vals=np.zeros(shape, np.float32),
            num_rows=num_rows,
            max_degree=d,
            dropped=0,
        )

    rows = np.asarray(rows, np.int64)
    counts = np.bincount(rows, minlength=num_rows)
    observed_max = int(counts.max())
    d = observed_max if max_degree is None else min(max_degree, observed_max)
    d = min(d, degree_cap)
    d = max(8, ((d + 7) // 8) * 8)

    nb = max(1, math.ceil(num_rows / block_rows))
    padded_rows = nb * block_rows

    nat = native.neighbor_blocks_native(
        rows, cols, vals, num_rows, padded_rows, d, seed
    ) if native.available() else None
    if nat is not None:
        ids, vv, _, dropped = nat
        if pad_id:
            # the C++ kernel zero-fills padding; vv==0 identifies exactly
            # those slots (genuine zero ratings were nudged to 1e-30 above)
            ids = np.where(vv == 0, np.int32(pad_id), ids)
        return NeighborBlocks(
            ids=ids.reshape(nb, block_rows, d),
            vals=vv.reshape(nb, block_rows, d),
            num_rows=num_rows,
            max_degree=d,
            dropped=dropped,
        )

    order = np.argsort(rows, kind="stable")
    r_sorted = rows[order]
    c_sorted = cols[order].astype(np.int32)
    v_sorted = vals[order].astype(np.float32)

    # position of each entry within its row
    starts = np.zeros(num_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    pos_in_row = np.arange(len(r_sorted)) - starts[r_sorted]

    dropped = 0
    overflow = counts > d
    if overflow.any():
        # deterministic per-row subsample: keep the d smallest
        # splitmix64(seed, row, pos) keys — same scheme as the C++ kernel
        key = _splitmix64(
            _splitmix64(np.uint64(seed) + r_sorted.astype(np.uint64))
            + pos_in_row.astype(np.uint64)
        )
        order2 = np.lexsort((key, r_sorted))
        rank = np.empty(len(r_sorted), dtype=np.int64)
        rank[order2] = np.arange(len(r_sorted)) - starts[r_sorted[order2]]
        keep = rank < d
        dropped = int((~keep).sum())
        r_sorted, c_sorted, v_sorted = r_sorted[keep], c_sorted[keep], v_sorted[keep]
        counts = np.bincount(r_sorted, minlength=num_rows)
        starts = np.zeros(num_rows + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        pos_in_row = np.arange(len(r_sorted)) - starts[r_sorted]

    ids = np.full((padded_rows, d), pad_id, np.int32)
    vv = np.zeros((padded_rows, d), np.float32)
    ids[r_sorted, pos_in_row] = c_sorted
    vv[r_sorted, pos_in_row] = v_sorted

    return NeighborBlocks(
        ids=ids.reshape(nb, block_rows, d),
        vals=vv.reshape(nb, block_rows, d),
        num_rows=num_rows,
        max_degree=d,
        dropped=dropped,
    )
