"""Host-side layout: COO ratings -> fixed-shape padded neighbor blocks.

The TPU ALS solver needs, for every user (resp. item), the list of rated
items (resp. rating users) as FIXED-SHAPE arrays — XLA cannot tile
variable-degree lists onto the MXU. This module builds that layout:

  ``NeighborBlocks``: ids [NB, B, D], vals [NB, B, D], mask [NB, B, D]

where B is the per-block row count (sharded over the mesh's data axis) and
D the padded max degree (capped; overflow entries are dropped highest-
degree-first with a deterministic subsample). This is the role MLlib ALS's
``InLinkBlock/OutLinkBlock`` shuffle layout plays in the reference's
training path (examples/.../ALSAlgorithm.scala -> org.apache.spark.mllib.
recommendation.ALS), re-thought for static shapes instead of shuffles:
layout is computed once on host with numpy sorts, then stays resident.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from .. import native

__all__ = [
    "DegreeBucket", "NeighborBlocks", "build_degree_buckets",
    "build_neighbor_blocks",
]

_splitmix64 = native.splitmix64_np


@dataclasses.dataclass
class NeighborBlocks:
    """Padded per-row neighbor lists, reshaped into blocks.

    Validity is encoded in ``vals``: padded slots are exactly 0; genuine
    zero values are nudged to 1e-30 at build time, so consumers derive
    the mask as ``vals != 0`` instead of carrying a third array (a third
    of the layout's memory and transfer at 20M-rating scale). ``mask`` is
    computed lazily for the few callers (tests) that want it explicitly.
    """

    ids: np.ndarray  # int32 [NB, B, D] neighbor indices (0 where padded)
    vals: np.ndarray  # float32 [NB, B, D] ratings/confidences (0 where padded)
    num_rows: int  # true number of rows (before padding to NB*B)
    max_degree: int  # D after capping
    dropped: int  # entries dropped by the degree cap

    @property
    def mask(self) -> np.ndarray:  # float32 [NB, B, D] 1.0 = real entry
        return (self.vals != 0).astype(np.float32)

    @property
    def padded_rows(self) -> int:
        return self.ids.shape[0] * self.ids.shape[1]


@dataclasses.dataclass
class DegreeBucket:
    """One degree tier of the bucketed layout: the rows whose degree fits
    this tier's D, plus the scatter indices mapping solved rows back into
    the factor matrix (out-of-range index = padding row, dropped by the
    scatter)."""

    blocks: NeighborBlocks
    row_ids: np.ndarray  # int32 [NB*B]; == num_total_rows for padding


def geometric_tiers(max_degree: int, *, base: int = 16,
                    ratio: float = 1.5) -> tuple[int, ...]:
    """Degree-tier edges in (rough) geometric progression, each a multiple
    of 8, ending exactly at ``max_degree`` rounded up to 8.

    Padding waste per row is bounded by the ratio between consecutive
    tiers (worst case a row's degree is one past the previous edge), so
    ratio 1.5 caps per-row padding at ~50% and averages ~20% — versus
    >3x with a handful of coarse tiers on zipf-skewed item degrees.
    """
    top = max(8, ((max_degree + 7) // 8) * 8)
    edges: list[int] = []
    d = float(base)
    while d < top:
        e = int(((int(d) + 7) // 8) * 8)
        if not edges or e > edges[-1]:
            edges.append(e)
        d *= ratio
    if not edges or edges[-1] < top:
        edges.append(top)
    else:
        edges[-1] = top
    return tuple(edges)


def build_degree_buckets(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    *,
    tiers: tuple[int, ...] | str = "auto",
    gather_budget: int = 2_000_000,
    seed: int = 0,
) -> list[DegreeBucket]:
    """ALX-style density-based layout: rows are grouped by degree tier so
    no tier wastes padding on light rows and heavy rows are not truncated.
    Per tier, the block row count is sized so one block's gathered factors
    stay within ``gather_budget`` elements (B * D <= budget) — bounding
    peak memory regardless of degree skew.

    ``tiers="auto"`` (default) derives geometric tiers from the observed
    max degree — ZERO entries dropped and bounded padding. An explicit
    tuple is honored but auto-extended with the observed max degree when
    rows exceed its last edge, so the layout is lossless either way.
    """
    counts = np.bincount(rows, minlength=num_rows) if len(rows) else np.zeros(num_rows, np.int64)
    observed_max = int(counts.max()) if len(counts) else 0
    if tiers == "auto":
        tiers = geometric_tiers(max(observed_max, 8))
    elif observed_max > tiers[-1]:
        # extend rather than drop: one extra tier holding the heaviest rows
        tiers = tuple(tiers) + (((observed_max + 7) // 8) * 8,)
    buckets: list[DegreeBucket] = []
    prev = 0
    for t_idx, tier_d in enumerate(tiers):
        last = t_idx == len(tiers) - 1
        sel = (counts > prev) & ((counts <= tier_d) | last)
        if t_idx == 0:
            sel |= counts == 0  # degree-0 rows ride the smallest tier
        row_idx = np.nonzero(sel)[0]
        prev = tier_d
        if len(row_idx) == 0:
            continue
        # remap selected rows to 0..len-1 for block building
        remap = np.full(num_rows, -1, np.int64)
        remap[row_idx] = np.arange(len(row_idx))
        in_sel = remap[rows] >= 0 if len(rows) else np.zeros(0, bool)
        b = build_neighbor_blocks(
            remap[rows[in_sel]].astype(np.int64),
            cols[in_sel],
            vals[in_sel],
            len(row_idx),
            block_rows=_block_rows_for(tier_d, gather_budget, len(row_idx)),
            degree_cap=tier_d,
            seed=seed,
        )
        ids_pad = np.full(b.padded_rows, num_rows, np.int32)  # padding sentinel
        ids_pad[: len(row_idx)] = row_idx.astype(np.int32)
        buckets.append(DegreeBucket(blocks=b, row_ids=ids_pad))
    return buckets


def _block_rows_for(tier_d: int, gather_budget: int, n_rows: int) -> int:
    b = max(8, gather_budget // max(tier_d, 8))
    # never larger than the tier itself: a tier with 20 rows must not pad
    # to a 8192-row block (the padding rows would gather garbage at full
    # per-block cost)
    b = min(8192, b, ((n_rows + 7) // 8) * 8)
    return max(8, ((b + 7) // 8) * 8)


def build_neighbor_blocks(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    *,
    block_rows: int = 4096,
    max_degree: int | None = None,
    degree_cap: int = 1024,
    seed: int = 0,
) -> NeighborBlocks:
    """Group (rows, cols, vals) COO triples by row into padded blocks.

    - D = min(max observed degree, ``degree_cap``) rounded up to a multiple
      of 8 (float32 sublane tiling).
    - Rows with degree > D keep a deterministic hash-keyed subsample (the
      same trade MLlib users make with sampling heavy users); the key is
      splitmix64(seed, row, pos) so the native C++ path and the numpy
      fallback produce identical layouts.
    - Rows padded to a multiple of ``block_rows``.

    Dispatches to the C++ counting-sort kernel (predictionio_tpu/native)
    when built; falls back to numpy sorts otherwise.
    """
    # Exact-zero values are nudged to a tiny epsilon so that downstream
    # consumers may derive the validity mask as ``vals != 0`` (the padded
    # slots are exactly 0) instead of carrying a separate mask array —
    # that mask is a third of the layout's device traffic at 20M-rating
    # scale. 1e-30 contributes nothing at float32/bfloat16 precision.
    vals = np.asarray(vals, np.float32)
    if len(vals) and (vals == 0).any():
        vals = np.where(vals == 0, np.float32(1e-30), vals)

    if len(rows) == 0:
        d = 8
        nb = max(1, math.ceil(max(num_rows, 1) / block_rows))
        shape = (nb, block_rows, d)
        return NeighborBlocks(
            ids=np.zeros(shape, np.int32),
            vals=np.zeros(shape, np.float32),
            num_rows=num_rows,
            max_degree=d,
            dropped=0,
        )

    rows = np.asarray(rows, np.int64)
    counts = np.bincount(rows, minlength=num_rows)
    observed_max = int(counts.max())
    d = observed_max if max_degree is None else min(max_degree, observed_max)
    d = min(d, degree_cap)
    d = max(8, ((d + 7) // 8) * 8)

    nb = max(1, math.ceil(num_rows / block_rows))
    padded_rows = nb * block_rows

    nat = native.neighbor_blocks_native(
        rows, cols, vals, num_rows, padded_rows, d, seed
    ) if native.available() else None
    if nat is not None:
        ids, vv, _, dropped = nat
        return NeighborBlocks(
            ids=ids.reshape(nb, block_rows, d),
            vals=vv.reshape(nb, block_rows, d),
            num_rows=num_rows,
            max_degree=d,
            dropped=dropped,
        )

    order = np.argsort(rows, kind="stable")
    r_sorted = rows[order]
    c_sorted = cols[order].astype(np.int32)
    v_sorted = vals[order].astype(np.float32)

    # position of each entry within its row
    starts = np.zeros(num_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    pos_in_row = np.arange(len(r_sorted)) - starts[r_sorted]

    dropped = 0
    overflow = counts > d
    if overflow.any():
        # deterministic per-row subsample: keep the d smallest
        # splitmix64(seed, row, pos) keys — same scheme as the C++ kernel
        key = _splitmix64(
            _splitmix64(np.uint64(seed) + r_sorted.astype(np.uint64))
            + pos_in_row.astype(np.uint64)
        )
        order2 = np.lexsort((key, r_sorted))
        rank = np.empty(len(r_sorted), dtype=np.int64)
        rank[order2] = np.arange(len(r_sorted)) - starts[r_sorted[order2]]
        keep = rank < d
        dropped = int((~keep).sum())
        r_sorted, c_sorted, v_sorted = r_sorted[keep], c_sorted[keep], v_sorted[keep]
        counts = np.bincount(r_sorted, minlength=num_rows)
        starts = np.zeros(num_rows + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        pos_in_row = np.arange(len(r_sorted)) - starts[r_sorted]

    ids = np.zeros((padded_rows, d), np.int32)
    vv = np.zeros((padded_rows, d), np.float32)
    ids[r_sorted, pos_in_row] = c_sorted
    vv[r_sorted, pos_in_row] = v_sorted

    return NeighborBlocks(
        ids=ids.reshape(nb, block_rows, d),
        vals=vv.reshape(nb, block_rows, d),
        num_rows=num_rows,
        max_degree=d,
        dropped=dropped,
    )
