"""Fused top-k retrieval — the serving hot path as a Pallas TPU kernel.

Every recommendation family in this framework ends serving with the same
shape of work: score a catalog ([N, D] factors / embeddings) against a
query vector and keep the top k (the reference does this per query on the
Spark driver with a full sort, e.g. examples/scala-parallel-similarproduct/
multi/src/main/scala/ALSAlgorithm.scala:146-200 and ALSModel.scala:200-219).
On TPU the naive form materializes a [B, N] score matrix in HBM and then
runs top_k over it — 2x the HBM traffic of the matmul itself for large N.

The kernel here streams item tiles through VMEM once: each grid step does
one [B, D] x [D, T] MXU matmul and merges the tile's scores into a running
[B, k] accumulator held in the (revisited) output block, so the full score
matrix never exists. k merge rounds per tile are VPU work over [B, k+T].

Off-TPU, serving auto-selects a plain-XLA top-k over the same padded
catalog (`_run_topk_xla` — fast compiled host code with the identical
output contract); ``interpret=True`` forces the kernel under the Pallas
interpreter (numerically identical, ~65x slower on CPU), the parity
path the kernel tests pin TPU semantics with.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time

import numpy as np

from ..obs.device import LEDGER
from ..obs.metrics import METRICS
from ..obs.waterfall import mark_stage, stage_sink_active
from ..workflow.faults import FAULTS

__all__ = ["topk_scores", "DeviceRetriever", "ShardedDeviceRetriever",
           "RetrievalServingMixin", "row_normalize", "ExecutableCache",
           "EXEC_CACHE", "choose_shard_count"]

# ISSUE 5: the executable cache's behavior under shape churn, scrapeable
# (stats() keeps its dict shape for /stats.json; same increments)
_M_EXEC_CACHE = METRICS.counter(
    "pio_exec_cache_total",
    "compiled-executable cache events (hit/miss/evict)",
    labelnames=("event",))


def row_normalize(x: np.ndarray) -> np.ndarray:
    """Unit-normalize rows (cosine scoring). The ONE home of the epsilon:
    the device similarity retriever and the host cosine fallback must
    score identically (test_als device/host parity pins it)."""
    x = np.asarray(x, np.float32)
    return x / (np.linalg.norm(x, axis=1, keepdims=True) + 1e-9)


#: Largest catalog whose indices are exact in float32 — above it the
#: packed single-pull result buffer would corrupt indices, so callers
#: fall back to the two-buffer path. One home for both retrievers.
PACKED_IDX_LIMIT = 1 << 24


class ExecutableCache:
    """THE bounded cache of compiled top-k serving executables — one home
    for what used to be three ad-hoc caches (`_build_call`'s lru_cache,
    `_build_xla_call`'s lru_cache, and ShardedDeviceRetriever's `_calls`
    dict), so a long-lived server has ONE executable budget and ONE set
    of hit/miss/eviction counters (surfaced through the engine server's
    /stats.json and the bench's emitted config).

    Keys are namespaced tuples carrying every shape the executable was
    specialized on. Entries pinned via ``pin()`` (the deploy path's
    AOT-pre-warmed hot serving shapes) are skipped by LRU eviction, so
    shape churn from odd client batch sizes can never evict the hot
    shape; the pin set itself is bounded (oldest pin unpinned past
    ``PIN_LIMIT``) so repeated /reloads of token-keyed sharded entries
    cannot grow it without bound.
    """

    PIN_LIMIT = 16

    def __init__(self, maxsize: int = 64):
        self.maxsize = max(1, maxsize)
        self._entries: dict = {}  # insertion order = LRU order
        self._pinned: dict = {}   # ordered set of pinned keys
        self._lock = threading.Lock()
        self._building: dict = {}  # key -> per-key build lock
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_build(self, key, build):
        """Return the cached value for ``key``, building (and inserting)
        it on a miss. ``build()`` runs OUTSIDE the cache lock — compiles
        take seconds and must not serialize the serving threads — but
        UNDER a per-key build lock, so two threads missing the same key
        compile it once: the loser waits and takes the winner's entry
        as a hit instead of burning a duplicate compile that the ledger
        would have to discard (ISSUE 16 satellite; the two-thread test
        pins exactly one pio_xla_compile_* observation)."""
        with self._lock:
            if key in self._entries:
                self.hits += 1
                val = self._entries.pop(key)
                self._entries[key] = val  # re-insert at the recent end
                _M_EXEC_CACHE.inc(event="hit")
                return val
            key_lock = self._building.setdefault(key, threading.Lock())
        with key_lock:
            with self._lock:
                if key in self._entries:
                    # a racing thread finished this build while we
                    # waited on the key lock: that's a hit, not a
                    # second compile
                    self.hits += 1
                    val = self._entries.pop(key)
                    self._entries[key] = val
                    _M_EXEC_CACHE.inc(event="hit")
                    return val
                self.misses += 1
            _M_EXEC_CACHE.inc(event="miss")
            t0 = time.perf_counter()
            val = build()
            # analysis probes outside the lock (they can walk the whole
            # HLO); residency bookkeeping (admit/discard) inside, in
            # lockstep with the insert/evict it accounts for — ISSUE 12
            entry = LEDGER.analyze(key, val, time.perf_counter() - t0)
            with self._lock:
                while len(self._entries) >= self.maxsize:
                    victim = next((k for k in self._entries
                                   if k not in self._pinned), None)
                    if victim is None:
                        break  # everything pinned: admit over budget
                    self._entries.pop(victim)
                    self.evictions += 1
                    _M_EXEC_CACHE.inc(event="evict")
                    LEDGER.discard(victim)
                self._entries[key] = val
                LEDGER.admit(entry)
                self._building.pop(key, None)
            return val

    def pin(self, key) -> None:
        """Exempt ``key`` from eviction (hot serving shapes)."""
        with self._lock:
            self._pinned.pop(key, None)
            self._pinned[key] = True
            while len(self._pinned) > self.PIN_LIMIT:
                self._pinned.pop(next(iter(self._pinned)))

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "size": len(self._entries),
                "pinned": len(self._pinned),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hitRate": (self.hits / total) if total else 0.0,
            }


#: Process-wide singleton: every retriever in the process shares one
#: executable budget (a server deploys several models over one backend).
EXEC_CACHE = ExecutableCache()

#: Distinguishes sharded-cache keys across retriever instances. A counter
#: rather than id(): id() values recycle after gc, and a recycled key
#: would serve a stale executable built over a DIFFERENT catalog.
_RETRIEVER_TOKENS = itertools.count()

#: Serializes multi-device (collective) executable launches process-wide.
#: Two collective programs launched concurrently from different threads
#: can interleave their per-device partitions on the backend's worker
#: pool; each partition then blocks in a rendezvous the other program's
#: partitions are occupying the pool for — a deadlock, not a slowdown
#: (pinned by test_microbatch's sharded-serving hammer). The lock is held
#: through block_until_ready so a launch fully drains before the next
#: one starts; single-device executables have no rendezvous and bypass
#: it. The retriever step is serialized across models either way: the
#: programs contend for the same device set.
_COLLECTIVE_LAUNCH_LOCK = threading.Lock()


def _pad_to(x, mult, axis, value=0.0):
    n = x.shape[axis]
    target = ((n + mult - 1) // mult) * mult
    if target == n:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - n)
    return np.pad(x, pad, constant_values=value) if isinstance(x, np.ndarray) else None


def _topk_kernel(q_ref, items_ref, vals_ref, idx_ref, *, k, tile_n, n_total):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        vals_ref[:] = jnp.full(vals_ref.shape, -jnp.inf, vals_ref.dtype)
        idx_ref[:] = jnp.full(idx_ref.shape, -1, idx_ref.dtype)

    q = q_ref[:]  # [B, D]
    tile = items_ref[:]  # [T, D]
    scores = jax.lax.dot_general(
        q, tile, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,  # full-f32 MXU passes: scores
        # must rank stably against host-side float32 references
    )  # [B, T]
    cand = j * tile_n + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(cand < n_total, scores, -jnp.inf)

    # threshold skip: a tile whose best score beats no row's current kth
    # value cannot change the result — only the matmul + max run for it
    # (with random scores most tiles skip, so the merge loop below is rare)
    kth = jnp.min(vals_ref[:])

    @pl.when(jnp.max(scores) > kth)
    def _():
        merged_v = jnp.concatenate([vals_ref[:], scores], axis=1)  # [B, k+T]
        merged_i = jnp.concatenate([idx_ref[:], cand], axis=1)

        B = merged_v.shape[0]
        col = jax.lax.broadcasted_iota(jnp.int32, merged_v.shape, 1)
        out_col = jax.lax.broadcasted_iota(jnp.int32, (B, k), 1)

        def extract(t, carry):
            # registers only — Mosaic forbids unaligned dynamic ref
            # writes, so the output slot is a one-hot, not pl.ds
            mv, out_v, out_i = carry
            m = jnp.max(mv, axis=1)  # [B]
            sel = mv == m[:, None]
            # first column holding the max (no cumsum in Mosaic):
            # min col index among argmax positions
            pick_col = jnp.min(jnp.where(sel, col, mv.shape[1]), axis=1)
            chosen = col == pick_col[:, None]
            pick = jnp.sum(jnp.where(chosen, merged_i, 0), axis=1)
            pick = jnp.where(jnp.isfinite(m), pick, -1).astype(jnp.int32)
            slot = out_col == t
            out_v = jnp.where(slot, m[:, None], out_v)
            out_i = jnp.where(slot, pick[:, None], out_i)
            return jnp.where(chosen, -jnp.inf, mv), out_v, out_i

        init = (
            merged_v,
            jnp.full((B, k), -jnp.inf, vals_ref.dtype),
            jnp.full((B, k), -1, idx_ref.dtype),
        )
        _, out_v, out_i = jax.lax.fori_loop(0, k, extract, init)
        vals_ref[:] = out_v
        idx_ref[:] = out_i


def _raw_call(B, D, N_pad, n_total, k, tile_n, interpret):
    """The un-jitted fused top-k pallas call — shared by the jitted
    serving entry (`_build_call`) and the device-time spin
    (`topk_device_seconds`), which wraps it in its own scan+jit."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (N_pad // tile_n,)
    kernel = functools.partial(_topk_kernel, k=k, tile_n=tile_n, n_total=n_total)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((B, D), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, D), lambda j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((B, k), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, k), lambda j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, k), jax.numpy.float32),
            jax.ShapeDtypeStruct((B, k), jax.numpy.int32),
        ],
        interpret=interpret,
    )


def _build_call(B, D, N_pad, n_total, k, tile_n, interpret, *, pin=False):
    """Compiled kernel + result packing: values and indices leave the
    device as ONE [B, 2k] f32 buffer. On remote-dispatch platforms each
    blocking host pull is a full round trip (measured ~67ms on the
    tunneled v5e) — two sequential pulls would double the serving latency
    the kernel's ~1ms of device time cannot explain. Indices are exact in
    f32 below 2^24; a larger catalog falls back to the two-buffer path.
    The executable is AOT-built (jit -> lower -> compile) into
    EXEC_CACHE; ``pin=True`` (the deploy path's pre-warm) exempts the
    shape from eviction."""
    key = ("kernel", B, D, N_pad, n_total, k, tile_n, interpret)
    out = EXEC_CACHE.get_or_build(key, lambda: _aot_with_packing(
        _raw_call(B, D, N_pad, n_total, k, tile_n, interpret),
        n_total, B, D, N_pad))
    if pin:
        EXEC_CACHE.pin(key)
    return out


def _aot_with_packing(call, n_total: int, B: int, D: int, N_pad: int):
    """The ONE home of the pack/no-pack policy for every single-device
    top-k builder (kernel and XLA): below PACKED_IDX_LIMIT, values and
    indices leave the device as one [B, 2k] f32 buffer (one host pull =
    one dispatch round trip); at/above it, the two-buffer path keeps
    indices exact. The executable is compiled AHEAD of the first call
    (``jax.jit(...).lower(...).compile()``) so a pre-warmed shape never
    pays tracing or compilation on the serving path. Returns (compiled
    executable, is_packed)."""
    import jax
    import jax.numpy as jnp

    if n_total >= PACKED_IDX_LIMIT:
        fn, is_packed = call, False
    else:
        def fn(q, items):
            vals, idx = call(q, items)
            return jnp.concatenate([vals, idx.astype(jnp.float32)], axis=1)

        is_packed = True
    compiled = jax.jit(fn).lower(
        jax.ShapeDtypeStruct((B, D), jnp.float32),
        jax.ShapeDtypeStruct((N_pad, D), jnp.float32),
    ).compile()
    return compiled, is_packed


def _raw_xla_call(n_total: int, k: int):
    """Un-jitted plain-XLA top-k over the full padded catalog — the
    serving path for NON-TPU backends, where running the Pallas kernel
    under ``interpret=True`` is a correctness tool, not a serving path
    (measured ~1.3 s/query on the CPU backend vs ~20 ms here at a 64k
    catalog). Same output contract as the kernel: padded/overflow slots
    carry value -inf and index -1."""
    import jax
    import jax.numpy as jnp

    def run(q, items):  # q [B, D_pad] f32, items [N_pad, D_pad] f32
        scores = jax.lax.dot_general(
            q, items, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,  # rank-stable vs the
            # kernel / sharded paths and host f32 references (DEFAULT
            # would allow TF32-class matmuls on some non-TPU backends)
        )
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(col < n_total, scores, -jnp.inf)
        vals, idx = jax.lax.top_k(scores, k)
        idx = jnp.where(jnp.isfinite(vals), idx, -1).astype(jnp.int32)
        return vals, idx

    return run


def _build_xla_call(B, D, N_pad, n_total, k, *, pin=False):
    """Compiled XLA top-k behind the shared packing policy, AOT-built
    into EXEC_CACHE like the kernel path (full shape key: the executable
    is compiled, not a retracing jit)."""
    key = ("xla", B, D, N_pad, n_total, k)
    out = EXEC_CACHE.get_or_build(key, lambda: _aot_with_packing(
        _raw_xla_call(n_total, k), n_total, B, D, N_pad))
    if pin:
        EXEC_CACHE.pin(key)
    return out


def _run_topk_xla(q: np.ndarray, items_dev, n_total: int, k: int):
    """Single-device entry, plain-XLA path (non-TPU serving)."""

    def invoke(qp, k_pad):
        call, is_packed = _build_xla_call(
            qp.shape[0], items_dev.shape[1], items_dev.shape[0],
            n_total, k_pad)
        # the compiled executable takes the padded numpy batch directly —
        # no jnp.asarray bounce through the default device
        return call(qp, items_dev), is_packed

    return _dispatch_topk(q, n_total, k, invoke)


def topk_device_seconds(retriever: "DeviceRetriever", k: int,
                        iters: int = 64) -> float:
    """Amortized per-query DEVICE time of the fused top-k kernel: `iters`
    single-query kernel invocations inside ONE jitted scan (one dispatch
    total), wall clock divided by `iters`. On remote-dispatch platforms a
    per-call wall p50 measures the client round trip, not the kernel —
    this is the honest device-side number to report next to it
    (VERDICT r2: the serving headline must split device time from the
    dispatch floor)."""
    import time

    import jax
    import jax.numpy as jnp

    d = retriever._items.shape[1]
    b_pad, k_pad = _query_shapes(1, min(k, retriever.n_total),
                                 retriever.n_total)
    if retriever._mode == "xla":
        call = _raw_xla_call(retriever.n_total, k_pad)
    else:
        call = _raw_call(b_pad, d, retriever._items.shape[0],
                         retriever.n_total, k_pad, retriever._tile_n,
                         retriever._mode == "interpret")
    qs = jnp.asarray(
        np.random.default_rng(0).normal(size=(iters, b_pad, d)),
        jnp.float32)

    @jax.jit
    def spin(qs, items):
        def body(acc, qi):
            vals, idx = call(qi, items)
            return acc + vals.sum() + idx.sum().astype(jnp.float32), None

        acc, _ = jax.lax.scan(body, jnp.float32(0), qs)
        return acc

    float(spin(qs, retriever._items))  # compile + warm
    t0 = time.perf_counter()
    float(spin(qs, retriever._items))  # blocks on the scalar result
    return (time.perf_counter() - t0) / iters


def _pad_items(items: np.ndarray, n_total: int, tile_n: int) -> tuple[np.ndarray, int]:
    """Feature-pad to the 128-lane width and row-pad to whole tiles;
    returns (padded items, clamped tile_n)."""
    it = _pad_to(items, 128, 1)
    tile_n = min(tile_n, max(128, ((n_total + 127) // 128) * 128))
    return _pad_to(it, tile_n, 0), tile_n


def _query_shapes(b: int, k_eff: int, n_total: int) -> tuple[int, int]:
    """Shape discipline on the serving hot path: batch padded to a power
    of two (>=8) and k rounded up to a multiple of 8, so traffic-dependent
    batch sizes / client-chosen num values map onto a handful of compiled
    kernels instead of one per (B, k) pair. The ONE home of this policy —
    `_run_topk` (serving) and `topk_device_seconds` (the bench's device-
    time spin) must time the same kernel shape."""
    b_pad = 8
    while b_pad < b:
        b_pad *= 2
    return b_pad, min(((k_eff + 7) // 8) * 8, n_total)


def _dispatch_topk(q: np.ndarray, n_total: int, k: int, invoke):
    """Query-side prep + result un-pad shared by EVERY top-k entry point
    (``topk_scores``, ``DeviceRetriever.topk``, ``ShardedDeviceRetriever
    .topk``) — one home so padding/empty-catalog/pack handling cannot
    drift between them. ``invoke(q_padded, k_pad)`` runs the compiled
    call and returns either a (vals, idx) tuple or the packed
    [B, 2*k_pad] f32 buffer (detected here by type)."""
    FAULTS.fire("retrieval.topk")  # chaos site: a hang here IS a hung
    # device call (workflow/faults.py); no-op unless a test armed it
    single = q.ndim == 1
    if single:
        q = q[None, :]
    k_eff = min(k, n_total)
    if n_total == 0 or k_eff <= 0:
        empty_v = np.zeros((q.shape[0], 0), np.float32)
        empty_i = np.zeros((q.shape[0], 0), np.int32)
        return (empty_v[0], empty_i[0]) if single else (empty_v, empty_i)
    b_orig = q.shape[0]
    b_pad, k_pad = _query_shapes(q.shape[0], k_eff, n_total)
    LEDGER.record_padding_waste(b_orig, b_pad)
    q = _pad_to(q, b_pad, 0)
    q = _pad_to(q, 128, 1)
    # Stage waterfall (obs/waterfall.py): when a serve request is being
    # attributed, split the invoke into dispatch (the call returning an
    # async device handle) and compute (block_until_ready delta). The
    # fence is conditional on an active sink so un-attributed callers
    # (training, bench device-spin) keep the async pipeline untouched.
    attributing = stage_sink_active()
    if attributing:
        mark_stage("host_assembly")
    out, is_packed = invoke(q, k_pad)
    if attributing:
        mark_stage("device_dispatch")
        try:
            import jax

            jax.block_until_ready(out)
        except Exception:
            pass  # numpy results / non-jax invokes: nothing to fence
        mark_stage("device_compute")
    if is_packed:
        host = np.asarray(out)  # packed: ONE pull
        vals = host[:b_orig, :k_eff]
        idx = host[:b_orig, k_pad:k_pad + k_eff].astype(np.int32)
    else:
        vals, idx = out
        vals = np.asarray(vals)[:b_orig, :k_eff]
        idx = np.asarray(idx)[:b_orig, :k_eff]
    if attributing:
        mark_stage("result_scatter")
    return (vals[0], idx[0]) if single else (vals, idx)


def _run_topk(q: np.ndarray, items_dev, n_total: int, k: int, tile_n: int,
              interpret: bool):
    """Single-device entry: fused Pallas kernel behind ``_dispatch_topk``."""

    def invoke(qp, k_pad):
        call, is_packed = _build_call(
            qp.shape[0], items_dev.shape[1], items_dev.shape[0], n_total,
            k_pad, tile_n, interpret,
        )
        return call(qp, items_dev), is_packed

    return _dispatch_topk(q, n_total, k, invoke)


def _resolve_topk_mode(interpret) -> str:
    """``interpret=None`` picks the serving path for the backend: the
    native Pallas kernel on TPU, plain XLA elsewhere (fast compiled
    host code). ``interpret=True`` forces the Pallas kernel under the
    interpreter — the TPU-semantics parity path tests use, ~65x slower
    than the XLA path on CPU, never a serving default. ``False`` forces
    the native kernel."""
    if interpret is None:
        import jax

        return "native" if jax.default_backend() == "tpu" else "xla"
    return "interpret" if interpret else "native"


def topk_scores(queries, items, k: int, *, tile_n: int = 512, interpret=None):
    """Top-k inner-product retrieval: (values [B, k], indices [B, k]).

    queries: [B, D] or [D]; items: [N, D]. Indices of padded/overflow slots
    are -1. Runs the Pallas kernel natively on TPU, plain XLA elsewhere;
    ``interpret=True`` forces the interpret-mode kernel (parity testing).
    """
    import jax.numpy as jnp

    mode = _resolve_topk_mode(interpret)
    q = np.asarray(queries, dtype=np.float32)
    it = np.asarray(items, dtype=np.float32)
    n_total = it.shape[0]
    it, tile_n = _pad_items(it, n_total, tile_n)
    items_dev = jnp.asarray(it)
    if mode == "xla":
        return _run_topk_xla(q, items_dev, n_total, k)
    return _run_topk(q, items_dev, n_total, k, tile_n, mode == "interpret")


class DeviceRetriever:
    """Catalog factors kept device-resident for serving: one host->device
    transfer at load/reload, then every query is a single compiled
    fused-top-k call (the engine server's /reload double-buffers by
    building a new DeviceRetriever and swapping the reference)."""

    def __init__(self, items: np.ndarray, *, tile_n: int = 512, interpret=None):
        import jax
        import jax.numpy as jnp

        self._mode = _resolve_topk_mode(interpret)
        it = np.asarray(items, dtype=np.float32)
        self.n_total, self.dim = it.shape
        it, self._tile_n = _pad_items(it, self.n_total, tile_n)
        self._items = jax.device_put(jnp.asarray(it))

    @property
    def lane_dim(self) -> int:
        """Query lane width this retriever's compiled programs take.
        ``topk`` accepts queries already padded to this width unchanged
        (``_dispatch_topk``'s lane pad is then a no-op), which is what
        lets the device-resident pipeline's gathered query matrix hand
        off with zero re-pad."""
        return int(self._items.shape[1])

    def topk(self, queries, k: int):
        """(values [B, k], indices [B, k]) — indices -1 beyond catalog."""
        q = np.asarray(queries, dtype=np.float32)
        if self._mode == "xla":
            return _run_topk_xla(q, self._items, self.n_total, k)
        return _run_topk(q, self._items, self.n_total, k, self._tile_n,
                         self._mode == "interpret")

    def prewarm(self, batch_sizes=(1,), ks=(10,)) -> list[tuple[int, int]]:
        """AOT-build and PIN the executables for the hot serving shapes,
        so the first query of a pre-warmed shape never pays a compile and
        executable-cache churn can never evict it. Called by the deploy
        path (workflow/create_server.Deployed) with the micro-batcher's
        max_batch and the single-query pad. Returns the distinct
        (b_pad, k_pad) shapes warmed."""
        warmed: list[tuple[int, int]] = []
        for b in batch_sizes:
            for k in ks:
                k_eff = min(k, self.n_total)
                if b <= 0 or k_eff <= 0:
                    continue
                b_pad, k_pad = _query_shapes(b, k_eff, self.n_total)
                if (b_pad, k_pad) in warmed:
                    continue
                if self._mode == "xla":
                    _build_xla_call(b_pad, self._items.shape[1],
                                    self._items.shape[0], self.n_total,
                                    k_pad, pin=True)
                else:
                    _build_call(b_pad, self._items.shape[1],
                                self._items.shape[0], self.n_total, k_pad,
                                self._tile_n, self._mode == "interpret",
                                pin=True)
                warmed.append((b_pad, k_pad))
        return warmed


class ShardedDeviceRetriever:
    """Catalog top-k with the item matrix SHARDED over a mesh axis — the
    serving-plane counterpart of model-parallel training: a catalog too
    large for one chip's HBM (or co-resident with a model-sharded training
    job) serves top-N without ever being replicated.

    Communication structure (the point of the design): each device scores
    its own [N/P, D] shard and reduces it to a local [B, k] candidate set
    inside ``shard_map``; the only collective is ONE all-gather of the
    packed [B, 2k] candidate buffers for the final merge — O(B*P*k) bytes
    over ICI, independent of catalog size. The cross-shard top-k-of-
    candidates merge ALSO runs inside the shard_map (every device merges
    the replicated [B, P*2k] gather redundantly — P*k is tiny), so the
    program leaves the device as the packed [B, 2k] result: one host
    pull, no GSPMD resharding step between the gather and the merge. No
    all-reduce, no all-to-all, and the [B, N] score matrix never exists
    globally (the reference's analog ships whole factor RDD partitions
    through Spark's shuffle to one driver-side sort, examples/scala-
    parallel-similarproduct/multi/src/main/scala/ALSAlgorithm.scala:
    146-200).

    API-compatible with ``DeviceRetriever`` (``topk``, ``n_total``,
    ``prewarm``): the serving mixin and micro-batcher use either
    interchangeably.
    """

    #: Where the cross-shard candidate merge runs. "device" = inside the
    #: shard_map program (one packed pull); the pre-r6 design merged in a
    #: GSPMD epilogue after an explicit replication constraint. The bench
    #: records this in its emitted config so the sweep is self-describing.
    merge = "device"

    def __init__(self, items: np.ndarray, mesh, *, axis: str = "model"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._mesh = mesh
        self._axis = axis
        self._nshards = int(mesh.shape[axis])
        it = np.asarray(items, dtype=np.float32)
        self.n_total, self.dim = it.shape
        it = _pad_to(it, 128, 1)
        # row-pad so every shard is equal-sized and lane-aligned
        it = _pad_to(it, 128 * self._nshards, 0)
        self._shard_rows = it.shape[0] // self._nshards
        # per-shard callback instead of a plain device_put: each process
        # materializes only its ADDRESSABLE shards, so the same code
        # serves from a mesh spanning multiple hosts (every host holds
        # the catalog on the host side; only 1/P lands in its HBM)
        self._items = jax.make_array_from_callback(
            it.shape, NamedSharding(mesh, P(axis, None)),
            lambda index: it[index])  # numpy slice: one direct
        # host->target-device transfer per shard (jnp.asarray here would
        # bounce every shard through the default device first)
        self._token = next(_RETRIEVER_TOKENS)  # EXEC_CACHE key namespace

    @property
    def lane_dim(self) -> int:
        """Query lane width (queries pre-padded to it pass through
        ``_dispatch_topk``'s lane pad unchanged — the pipeline's gather
        handoff contract, same as ``DeviceRetriever.lane_dim``)."""
        return int(self._items.shape[1])

    def _call_for(self, b_pad: int, k_local: int, k_out: int, *,
                  pin: bool = False):
        key = ("sharded", self._token, b_pad, k_local, k_out)
        fn = EXEC_CACHE.get_or_build(
            key, lambda: self._build(b_pad, k_local, k_out))
        if pin:
            EXEC_CACHE.pin(key)
        return fn

    def _build(self, b_pad: int, k_local: int, k_out: int):
        # k_local: per-shard candidates (<= shard rows; a global top-k_out
        # set takes at most shard_rows entries from any one shard, so
        # k_local = min(k_out, shard_rows) is exact, not approximate).
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.collectives import get_shard_map

        axis, n_total, S = self._axis, self.n_total, self._shard_rows
        nsh = self._nshards
        packed = n_total < PACKED_IDX_LIMIT
        shard_map = get_shard_map()

        def local_merge(q, shard):  # q [B, D] replicated; shard [S, D]
            scores = jax.lax.dot_general(
                q, shard, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,  # rank-stable vs the
                # single-device kernel and the host f32 reference
            )  # [B, S]
            offset = jax.lax.axis_index(axis) * S
            cand = offset + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            scores = jnp.where(cand < n_total, scores, -jnp.inf)
            v, i = jax.lax.top_k(scores, k_local)
            i = jnp.take_along_axis(cand, i, axis=1)
            # the gather is shard-major, and within a shard top_k orders
            # ties by ascending index — so candidate order in the merged
            # buffer IS ascending global index per score, and the final
            # top_k tie-breaks exactly like the full-catalog top_k
            # (bitwise parity, pinned by test_sharded_bitwise_parity)
            if packed:
                # indices ride the gather as f32 (exact below 2^24):
                # ONE collective instead of two
                buf = jnp.concatenate([v, i.astype(jnp.float32)], axis=1)
                g = jax.lax.all_gather(buf, axis, axis=1, tiled=True)
                g = g.reshape(g.shape[0], nsh, 2 * k_local)
                v_all = g[:, :, :k_local].reshape(-1, nsh * k_local)
                i_all = g[:, :, k_local:].reshape(
                    -1, nsh * k_local).astype(jnp.int32)
            else:
                v_all = jax.lax.all_gather(v, axis, axis=1, tiled=True)
                i_all = jax.lax.all_gather(i, axis, axis=1, tiled=True)
            mv, sel = jax.lax.top_k(v_all, k_out)
            mi = jnp.take_along_axis(i_all, sel, axis=1)
            mi = jnp.where(jnp.isfinite(mv), mi, -1)
            if packed:  # packed result: ONE host pull
                return jnp.concatenate([mv, mi.astype(jnp.float32)], axis=1)
            return mv, mi

        def run(q, items):
            return shard_map(
                local_merge, mesh=self._mesh,
                in_specs=(P(), P(axis, None)),
                out_specs=P() if packed else (P(), P()),
            )(q, items)

        return jax.jit(run, in_shardings=(
            NamedSharding(self._mesh, P()),
            NamedSharding(self._mesh, P(axis, None)),
        )).lower(
            jax.ShapeDtypeStruct((b_pad, self._items.shape[1]), jnp.float32),
            jax.ShapeDtypeStruct(self._items.shape, jnp.float32),
        ).compile()

    def topk(self, queries, k: int):
        """(values [B, k], indices [B, k]) — indices -1 beyond catalog.
        Accepts [D] or [B, D]; exact parity with DeviceRetriever.topk
        (pinned by test_retrieval.test_sharded_matches_single_device)."""
        import jax

        def invoke(qp, k_pad):
            k_local = min(k_pad, self._shard_rows)
            call = self._call_for(qp.shape[0], k_local, k_pad)
            # padded numpy batch straight into the compiled executable
            # (an asarray here would land it on the default device first,
            # just to be resharded by the in_shardings)
            with _COLLECTIVE_LAUNCH_LOCK:
                out = jax.block_until_ready(call(qp, self._items))
            return out, self.n_total < PACKED_IDX_LIMIT

        return _dispatch_topk(np.asarray(queries, dtype=np.float32),
                              self.n_total, k, invoke)

    def prewarm(self, batch_sizes=(1,), ks=(10,)) -> list[tuple[int, int]]:
        """AOT-build and PIN the hot serving shapes' executables — same
        contract as ``DeviceRetriever.prewarm``."""
        warmed: list[tuple[int, int]] = []
        for b in batch_sizes:
            for k in ks:
                k_eff = min(k, self.n_total)
                if b <= 0 or k_eff <= 0:
                    continue
                b_pad, k_pad = _query_shapes(b, k_eff, self.n_total)
                if (b_pad, k_pad) in warmed:
                    continue
                self._call_for(b_pad, min(k_pad, self._shard_rows), k_pad,
                               pin=True)
                warmed.append((b_pad, k_pad))
        return warmed


#: choose_shard_count's cost model, in scanned-item units per query:
#: sharding w ways scans N/w rows per device but pays the cross-shard
#: candidate merge — a near-fixed collective/launch cost plus a small
#: per-way term. Calibrated against BENCH_r05's measured inversion
#: (8-way 2606 qps < 1-way 3427 qps at a 64k catalog: the merge costs
#: more than 64k/8-per-way saves, so the crossover sits near ~1M rows).
MERGE_COST_FIXED = 192_000
MERGE_COST_PER_WAY = 16_000


def choose_shard_count(n_total: int, ndev: int, *,
                       merge_fixed: int = MERGE_COST_FIXED,
                       merge_per_way: int = MERGE_COST_PER_WAY) -> int:
    """Shard count for a catalog of ``n_total`` rows on ``ndev`` devices:
    argmin over power-of-two widths of ``N/w + (w > 1) * (merge_fixed +
    merge_per_way * w)``. Closes the BENCH_r05 sharded-serving inversion
    by construction — a width is only picked when its per-shard scan
    saving exceeds the merge it adds, so 8-way can never be selected
    where the model says 1-way is faster. Deploy (``--retriever-mesh
    auto``) and ``pio bench serve --ways auto`` both route through here
    at executable-build time."""
    ndev = max(1, int(ndev))
    best_w, best_cost = 1, float(max(0, n_total))
    w = 2
    while w <= ndev:
        cost = n_total / w + merge_fixed + merge_per_way * w
        if cost < best_cost:
            best_w, best_cost = w, cost
        w *= 2
    return best_w


class RetrievalServingMixin:
    """Serving-side device retrieval for models whose predict step is
    "score a catalog matrix against one query row, keep top-k" (ALS
    factors, two-tower embeddings, ...).

    Provides ``attach_retriever`` (build a DeviceRetriever over the
    catalog attribute named by ``_retrieval_attr``) and keeps the device
    handle out of pickled MODELDATA blobs.
    """

    _retrieval_attr = "item_factors"
    _retrieval_ids_attr = "item_ids"

    def top_n_from_catalog(self, query_vec, num: int) -> list[tuple[str, float]]:
        """[(id, score)] top-N of catalog·query: through the device
        retriever when attached, else a host argpartition. The single
        home of this logic for every retrieval-serving model."""
        ids = getattr(self, self._retrieval_ids_attr)
        inv = ids.inverse
        via_device = self._retriever_topk(query_vec, num, inv)
        if via_device is not None:
            return via_device
        catalog = getattr(self, self._retrieval_attr)
        scores = catalog @ np.asarray(query_vec, catalog.dtype)
        num = min(num, len(scores))
        if num <= 0:
            return []
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        return [(inv[int(i)], float(scores[i])) for i in top]

    def top_n_batch(self, query_mat, num: int) -> list[list[tuple[str, float]]]:
        """Batched ``top_n_from_catalog``: one fused device call (or one
        host matmul) for a whole micro-batch of query vectors [B, D]."""
        q = np.asarray(query_mat, np.float32)
        if q.ndim != 2 or len(q) == 0:
            return []
        ids = getattr(self, self._retrieval_ids_attr)
        inv = ids.inverse
        retriever = getattr(self, "_retriever", None)
        if retriever is not None:
            vals, idx = retriever.topk(q, num)
            return [
                [(inv[int(i)], float(v)) for v, i in zip(vr, ir) if i >= 0]
                for vr, ir in zip(vals, idx)
            ]
        catalog = getattr(self, self._retrieval_attr)
        scores = q @ catalog.T  # [B, N]
        k = min(num, scores.shape[1])
        if k <= 0:
            return [[] for _ in range(len(q))]
        top = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        out = []
        for r, t in zip(scores, top):
            t = t[np.argsort(-r[t])]
            out.append([(inv[int(i)], float(r[i])) for i in t])
        return out

    _query_attr = "user_factors"
    _query_ids_attr = "user_ids"

    def batch_recommend(self, users: list, nums: list) -> list[list[tuple[str, float]]]:
        """Per-user top-N for a whole micro-batch in one device call;
        unknown users get []. The single home of the unknown-user/kmax/
        trim dance for every retrieval-serving model's batch_predict.

        With a serving pipeline attached (ISSUE 16), the host side of
        this shrinks to ONE vectorized id->row translation: the factor
        gather, padding and scoring all run in the pipeline's compiled
        device programs. The compacted row batch and the trim dance are
        identical to the legacy path, so results are bit-for-bit the
        same (the capture/replay parity tests pin it)."""
        uids = getattr(self, self._query_ids_attr)
        qmat = getattr(self, self._query_attr)
        out: list = [[] for _ in users]
        pipe = getattr(self, "_pipeline", None)
        if pipe is not None and pipe.n_rows == len(qmat):
            rows = uids.map_array(users)
            known = np.flatnonzero(rows >= 0)
            if known.size == 0:
                return out
            kmax = max(max(nums[j] for j in known), 0)
            vals, idx = pipe.topk_rows(rows[known], kmax)
            inv = getattr(self, self._retrieval_ids_attr).inverse
            for j, vr, ir in zip(known.tolist(), vals, idx):
                rec = [(inv[int(i)], float(v))
                       for v, i in zip(vr, ir) if i >= 0]
                out[j] = rec[: max(nums[j], 0)]
            return out
        known = [(j, uids.get(u)) for j, u in enumerate(users)]
        known = [(j, r) for j, r in known if r is not None]
        if not known:
            return out
        kmax = max(max(nums[j] for j, _ in known), 0)
        recs = self.top_n_batch(qmat[[r for _, r in known]], kmax)
        for (j, _r), rec in zip(known, recs):
            out[j] = rec[: max(nums[j], 0)]
        return out

    def attach_retriever(self, interpret=None) -> None:
        """Move the catalog device-resident and serve top-N through the
        fused Pallas retrieval kernel. Called by the engine server at
        deploy/reload time on TPU backends; replacing the retriever
        wholesale is the /reload double-buffer swap."""
        self._retriever = DeviceRetriever(
            getattr(self, self._retrieval_attr), interpret=interpret
        )

    def attach_ann_retriever(self, interpret=None, **params) -> None:
        """Serve top-N through the IVF approximate-MIPS index
        (ops/ann.py AnnRetriever) — same serving surface, sub-linear
        scan. ``params`` is the engine-params ``retrieval`` block minus
        ``mode`` (nprobe / quantize / n_cells / min_items /
        kmeans_iters / kmeans_sample / max_cell_factor / seed). Small
        catalogs and failed builds fall back to exact inside the
        retriever; /reload swaps it like any retriever."""
        from .ann import AnnRetriever

        self._retriever = AnnRetriever(
            getattr(self, self._retrieval_attr), interpret=interpret,
            **params)

    def attach_pipeline(self) -> None:
        """Make the QUERY side of serving device-resident too (ISSUE
        16): upload the user-factor table into a ServingPipeline over
        the already-attached retriever, so ``batch_recommend`` ships
        only int32 row indices per request. Requires a retriever
        (exact, ANN or sharded — the pipeline adapts); /reload builds a
        fresh bundle and re-attaches, delta patches ``refresh`` the
        table copy-on-write without invalidating compiled programs."""
        from .pipeline import ServingPipeline

        self._pipeline = ServingPipeline(
            getattr(self, self._query_attr),
            getattr(self, "_retriever", None))

    def attach_sharded_retriever(self, mesh, *, axis: str = "model") -> None:
        """Serve top-N from a catalog SHARDED over ``mesh``'s ``axis`` —
        same serving surface, ShardedDeviceRetriever underneath. For
        catalogs past one chip's HBM or deployments co-resident with a
        model-sharded trainer; /reload swaps it like any retriever."""
        self._retriever = ShardedDeviceRetriever(
            getattr(self, self._retrieval_attr), mesh, axis=axis)

    def attach_similarity_retriever(self, interpret=None) -> None:
        """Row-NORMALIZED catalog retriever: cosine similar-items serving
        (the similarproduct family) as the same fused top-k kernel — an
        aggregate cosine over query items is one retrieval with the
        summed normalized query vectors (Σ over k query items of
        cn·qn_k = cn·Σqn_k)."""
        cn = row_normalize(getattr(self, self._retrieval_attr))
        self._sim_retriever = DeviceRetriever(cn, interpret=interpret)

    def attach_sharded_similarity_retriever(self, mesh, *,
                                            axis: str = "model") -> None:
        """Sharded variant of ``attach_similarity_retriever``: the
        normalized catalog shards over ``mesh``'s ``axis`` so cosine
        similar-items serving scales past one chip's HBM like the
        inner-product path does."""
        cn = row_normalize(getattr(self, self._retrieval_attr))
        self._sim_retriever = ShardedDeviceRetriever(cn, mesh, axis=axis)

    def __getstate__(self):
        state = dict(self.__dict__)
        # device arrays and derived caches never enter MODELDATA
        state.pop("_retriever", None)
        state.pop("_sim_retriever", None)
        state.pop("_pipeline", None)
        state.pop("_vtv_cache", None)
        state.pop("_cn_cache", None)
        return state

    def _retriever_topk(self, query_vec, num, inverse_ids):
        """[(id, score)] via the attached retriever, or None if detached."""
        retriever = getattr(self, "_retriever", None)
        if retriever is None:
            return None
        vals, idx = retriever.topk(query_vec, num)
        return [(inverse_ids[int(i)], float(v))
                for v, i in zip(vals, idx) if i >= 0]
