"""Fused top-k retrieval — the serving hot path as a Pallas TPU kernel.

Every recommendation family in this framework ends serving with the same
shape of work: score a catalog ([N, D] factors / embeddings) against a
query vector and keep the top k (the reference does this per query on the
Spark driver with a full sort, e.g. examples/scala-parallel-similarproduct/
multi/src/main/scala/ALSAlgorithm.scala:146-200 and ALSModel.scala:200-219).
On TPU the naive form materializes a [B, N] score matrix in HBM and then
runs top_k over it — 2x the HBM traffic of the matmul itself for large N.

The kernel here streams item tiles through VMEM once: each grid step does
one [B, D] x [D, T] MXU matmul and merges the tile's scores into a running
[B, k] accumulator held in the (revisited) output block, so the full score
matrix never exists. k merge rounds per tile are VPU work over [B, k+T].

CPU/test path: the same kernel under ``interpret=True`` (numerically
identical); auto-selected off-TPU.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["topk_scores", "DeviceRetriever", "RetrievalServingMixin", "row_normalize"]


def row_normalize(x: np.ndarray) -> np.ndarray:
    """Unit-normalize rows (cosine scoring). The ONE home of the epsilon:
    the device similarity retriever and the host cosine fallback must
    score identically (test_als device/host parity pins it)."""
    x = np.asarray(x, np.float32)
    return x / (np.linalg.norm(x, axis=1, keepdims=True) + 1e-9)


def _pad_to(x, mult, axis, value=0.0):
    n = x.shape[axis]
    target = ((n + mult - 1) // mult) * mult
    if target == n:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - n)
    return np.pad(x, pad, constant_values=value) if isinstance(x, np.ndarray) else None


def _topk_kernel(q_ref, items_ref, vals_ref, idx_ref, *, k, tile_n, n_total):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        vals_ref[:] = jnp.full(vals_ref.shape, -jnp.inf, vals_ref.dtype)
        idx_ref[:] = jnp.full(idx_ref.shape, -1, idx_ref.dtype)

    q = q_ref[:]  # [B, D]
    tile = items_ref[:]  # [T, D]
    scores = jax.lax.dot_general(
        q, tile, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,  # full-f32 MXU passes: scores
        # must rank stably against host-side float32 references
    )  # [B, T]
    cand = j * tile_n + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(cand < n_total, scores, -jnp.inf)

    # threshold skip: a tile whose best score beats no row's current kth
    # value cannot change the result — only the matmul + max run for it
    # (with random scores most tiles skip, so the merge loop below is rare)
    kth = jnp.min(vals_ref[:])

    @pl.when(jnp.max(scores) > kth)
    def _():
        merged_v = jnp.concatenate([vals_ref[:], scores], axis=1)  # [B, k+T]
        merged_i = jnp.concatenate([idx_ref[:], cand], axis=1)

        B = merged_v.shape[0]
        col = jax.lax.broadcasted_iota(jnp.int32, merged_v.shape, 1)
        out_col = jax.lax.broadcasted_iota(jnp.int32, (B, k), 1)

        def extract(t, carry):
            # registers only — Mosaic forbids unaligned dynamic ref
            # writes, so the output slot is a one-hot, not pl.ds
            mv, out_v, out_i = carry
            m = jnp.max(mv, axis=1)  # [B]
            sel = mv == m[:, None]
            # first column holding the max (no cumsum in Mosaic):
            # min col index among argmax positions
            pick_col = jnp.min(jnp.where(sel, col, mv.shape[1]), axis=1)
            chosen = col == pick_col[:, None]
            pick = jnp.sum(jnp.where(chosen, merged_i, 0), axis=1)
            pick = jnp.where(jnp.isfinite(m), pick, -1).astype(jnp.int32)
            slot = out_col == t
            out_v = jnp.where(slot, m[:, None], out_v)
            out_i = jnp.where(slot, pick[:, None], out_i)
            return jnp.where(chosen, -jnp.inf, mv), out_v, out_i

        init = (
            merged_v,
            jnp.full((B, k), -jnp.inf, vals_ref.dtype),
            jnp.full((B, k), -1, idx_ref.dtype),
        )
        _, out_v, out_i = jax.lax.fori_loop(0, k, extract, init)
        vals_ref[:] = out_v
        idx_ref[:] = out_i


def _raw_call(B, D, N_pad, n_total, k, tile_n, interpret):
    """The un-jitted fused top-k pallas call — shared by the jitted
    serving entry (`_build_call`) and the device-time spin
    (`topk_device_seconds`), which wraps it in its own scan+jit."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (N_pad // tile_n,)
    kernel = functools.partial(_topk_kernel, k=k, tile_n=tile_n, n_total=n_total)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((B, D), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, D), lambda j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((B, k), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, k), lambda j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, k), jax.numpy.float32),
            jax.ShapeDtypeStruct((B, k), jax.numpy.int32),
        ],
        interpret=interpret,
    )


@functools.partial(
    # bounded: a long-lived server reloading a growing catalog must not
    # accumulate one compiled kernel per historical catalog size. 32 covers
    # the pow2-padded batch sizes x rounded k values of steady serving.
    functools.lru_cache(maxsize=32),
)
def _build_call(B, D, N_pad, n_total, k, tile_n, interpret):
    """Jitted kernel + result packing: values and indices leave the device
    as ONE [B, 2k] f32 buffer. On remote-dispatch platforms each blocking
    host pull is a full round trip (measured ~67ms on the tunneled v5e) —
    two sequential pulls would double the serving latency the kernel's
    ~1ms of device time cannot explain. Indices are exact in f32 below
    2^24; a larger catalog falls back to the two-buffer path."""
    import jax
    import jax.numpy as jnp

    call = _raw_call(B, D, N_pad, n_total, k, tile_n, interpret)
    if n_total >= 1 << 24:
        return jax.jit(call), False

    def packed(q, items):
        vals, idx = call(q, items)
        return jnp.concatenate([vals, idx.astype(jnp.float32)], axis=1)

    return jax.jit(packed), True


def topk_device_seconds(retriever: "DeviceRetriever", k: int,
                        iters: int = 64) -> float:
    """Amortized per-query DEVICE time of the fused top-k kernel: `iters`
    single-query kernel invocations inside ONE jitted scan (one dispatch
    total), wall clock divided by `iters`. On remote-dispatch platforms a
    per-call wall p50 measures the client round trip, not the kernel —
    this is the honest device-side number to report next to it
    (VERDICT r2: the serving headline must split device time from the
    dispatch floor)."""
    import time

    import jax
    import jax.numpy as jnp

    d = retriever._items.shape[1]
    b_pad, k_pad = _query_shapes(1, min(k, retriever.n_total),
                                 retriever.n_total)
    call = _raw_call(b_pad, d, retriever._items.shape[0], retriever.n_total,
                     k_pad, retriever._tile_n, retriever._interpret)
    qs = jnp.asarray(
        np.random.default_rng(0).normal(size=(iters, b_pad, d)),
        jnp.float32)

    @jax.jit
    def spin(qs, items):
        def body(acc, qi):
            vals, idx = call(qi, items)
            return acc + vals.sum() + idx.sum().astype(jnp.float32), None

        acc, _ = jax.lax.scan(body, jnp.float32(0), qs)
        return acc

    float(spin(qs, retriever._items))  # compile + warm
    t0 = time.perf_counter()
    float(spin(qs, retriever._items))  # blocks on the scalar result
    return (time.perf_counter() - t0) / iters


def _pad_items(items: np.ndarray, n_total: int, tile_n: int) -> tuple[np.ndarray, int]:
    """Feature-pad to the 128-lane width and row-pad to whole tiles;
    returns (padded items, clamped tile_n)."""
    it = _pad_to(items, 128, 1)
    tile_n = min(tile_n, max(128, ((n_total + 127) // 128) * 128))
    return _pad_to(it, tile_n, 0), tile_n


def _query_shapes(b: int, k_eff: int, n_total: int) -> tuple[int, int]:
    """Shape discipline on the serving hot path: batch padded to a power
    of two (>=8) and k rounded up to a multiple of 8, so traffic-dependent
    batch sizes / client-chosen num values map onto a handful of compiled
    kernels instead of one per (B, k) pair. The ONE home of this policy —
    `_run_topk` (serving) and `topk_device_seconds` (the bench's device-
    time spin) must time the same kernel shape."""
    b_pad = 8
    while b_pad < b:
        b_pad *= 2
    return b_pad, min(((k_eff + 7) // 8) * 8, n_total)


def _run_topk(q: np.ndarray, items_dev, n_total: int, k: int, tile_n: int,
              interpret: bool):
    """Shared query-side prep + kernel call + un-pad for ``topk_scores``
    and ``DeviceRetriever.topk`` (one home so padding/empty-catalog
    handling cannot drift between the two entry points)."""
    import jax.numpy as jnp

    single = q.ndim == 1
    if single:
        q = q[None, :]
    k_eff = min(k, n_total)
    if n_total == 0 or k_eff <= 0:
        empty_v = np.zeros((q.shape[0], 0), np.float32)
        empty_i = np.zeros((q.shape[0], 0), np.int32)
        return (empty_v[0], empty_i[0]) if single else (empty_v, empty_i)
    b_orig = q.shape[0]
    b_pad, k_pad = _query_shapes(q.shape[0], k_eff, n_total)
    q = _pad_to(q, b_pad, 0)
    q = _pad_to(q, 128, 1)
    call, is_packed = _build_call(
        q.shape[0], items_dev.shape[1], items_dev.shape[0], n_total, k_pad,
        tile_n, interpret,
    )
    if is_packed:
        host = np.asarray(call(jnp.asarray(q), items_dev))  # ONE pull
        vals = host[:b_orig, :k_eff]
        idx = host[:b_orig, k_pad:k_pad + k_eff].astype(np.int32)
    else:
        vals, idx = call(jnp.asarray(q), items_dev)
        vals = np.asarray(vals)[:b_orig, :k_eff]
        idx = np.asarray(idx)[:b_orig, :k_eff]
    return (vals[0], idx[0]) if single else (vals, idx)


def topk_scores(queries, items, k: int, *, tile_n: int = 512, interpret=None):
    """Top-k inner-product retrieval: (values [B, k], indices [B, k]).

    queries: [B, D] or [D]; items: [N, D]. Indices of padded/overflow slots
    are -1. Runs the Pallas kernel natively on TPU, in interpreter mode
    elsewhere.
    """
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q = np.asarray(queries, dtype=np.float32)
    it = np.asarray(items, dtype=np.float32)
    n_total = it.shape[0]
    it, tile_n = _pad_items(it, n_total, tile_n)
    return _run_topk(q, jnp.asarray(it), n_total, k, tile_n, bool(interpret))


class DeviceRetriever:
    """Catalog factors kept device-resident for serving: one host->device
    transfer at load/reload, then every query is a single compiled
    fused-top-k call (the engine server's /reload double-buffers by
    building a new DeviceRetriever and swapping the reference)."""

    def __init__(self, items: np.ndarray, *, tile_n: int = 512, interpret=None):
        import jax
        import jax.numpy as jnp

        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self._interpret = bool(interpret)
        it = np.asarray(items, dtype=np.float32)
        self.n_total, self.dim = it.shape
        it, self._tile_n = _pad_items(it, self.n_total, tile_n)
        self._items = jax.device_put(jnp.asarray(it))

    def topk(self, queries, k: int):
        """(values [B, k], indices [B, k]) — indices -1 beyond catalog."""
        q = np.asarray(queries, dtype=np.float32)
        return _run_topk(q, self._items, self.n_total, k, self._tile_n,
                         self._interpret)


class RetrievalServingMixin:
    """Serving-side device retrieval for models whose predict step is
    "score a catalog matrix against one query row, keep top-k" (ALS
    factors, two-tower embeddings, ...).

    Provides ``attach_retriever`` (build a DeviceRetriever over the
    catalog attribute named by ``_retrieval_attr``) and keeps the device
    handle out of pickled MODELDATA blobs.
    """

    _retrieval_attr = "item_factors"
    _retrieval_ids_attr = "item_ids"

    def top_n_from_catalog(self, query_vec, num: int) -> list[tuple[str, float]]:
        """[(id, score)] top-N of catalog·query: through the device
        retriever when attached, else a host argpartition. The single
        home of this logic for every retrieval-serving model."""
        ids = getattr(self, self._retrieval_ids_attr)
        inv = ids.inverse
        via_device = self._retriever_topk(query_vec, num, inv)
        if via_device is not None:
            return via_device
        catalog = getattr(self, self._retrieval_attr)
        scores = catalog @ np.asarray(query_vec, catalog.dtype)
        num = min(num, len(scores))
        if num <= 0:
            return []
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        return [(inv[int(i)], float(scores[i])) for i in top]

    def top_n_batch(self, query_mat, num: int) -> list[list[tuple[str, float]]]:
        """Batched ``top_n_from_catalog``: one fused device call (or one
        host matmul) for a whole micro-batch of query vectors [B, D]."""
        q = np.asarray(query_mat, np.float32)
        if q.ndim != 2 or len(q) == 0:
            return []
        ids = getattr(self, self._retrieval_ids_attr)
        inv = ids.inverse
        retriever = getattr(self, "_retriever", None)
        if retriever is not None:
            vals, idx = retriever.topk(q, num)
            return [
                [(inv[int(i)], float(v)) for v, i in zip(vr, ir) if i >= 0]
                for vr, ir in zip(vals, idx)
            ]
        catalog = getattr(self, self._retrieval_attr)
        scores = q @ catalog.T  # [B, N]
        k = min(num, scores.shape[1])
        if k <= 0:
            return [[] for _ in range(len(q))]
        top = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        out = []
        for r, t in zip(scores, top):
            t = t[np.argsort(-r[t])]
            out.append([(inv[int(i)], float(r[i])) for i in t])
        return out

    _query_attr = "user_factors"
    _query_ids_attr = "user_ids"

    def batch_recommend(self, users: list, nums: list) -> list[list[tuple[str, float]]]:
        """Per-user top-N for a whole micro-batch in one device call;
        unknown users get []. The single home of the unknown-user/kmax/
        trim dance for every retrieval-serving model's batch_predict."""
        uids = getattr(self, self._query_ids_attr)
        qmat = getattr(self, self._query_attr)
        out: list = [[] for _ in users]
        known = [(j, uids.get(u)) for j, u in enumerate(users)]
        known = [(j, r) for j, r in known if r is not None]
        if not known:
            return out
        kmax = max(max(nums[j] for j, _ in known), 0)
        recs = self.top_n_batch(qmat[[r for _, r in known]], kmax)
        for (j, _r), rec in zip(known, recs):
            out[j] = rec[: max(nums[j], 0)]
        return out

    def attach_retriever(self, interpret=None) -> None:
        """Move the catalog device-resident and serve top-N through the
        fused Pallas retrieval kernel. Called by the engine server at
        deploy/reload time on TPU backends; replacing the retriever
        wholesale is the /reload double-buffer swap."""
        self._retriever = DeviceRetriever(
            getattr(self, self._retrieval_attr), interpret=interpret
        )

    def attach_similarity_retriever(self, interpret=None) -> None:
        """Row-NORMALIZED catalog retriever: cosine similar-items serving
        (the similarproduct family) as the same fused top-k kernel — an
        aggregate cosine over query items is one retrieval with the
        summed normalized query vectors (Σ over k query items of
        cn·qn_k = cn·Σqn_k)."""
        cn = row_normalize(getattr(self, self._retrieval_attr))
        self._sim_retriever = DeviceRetriever(cn, interpret=interpret)

    def __getstate__(self):
        state = dict(self.__dict__)
        # device arrays never enter MODELDATA
        state.pop("_retriever", None)
        state.pop("_sim_retriever", None)
        return state

    def _retriever_topk(self, query_vec, num, inverse_ids):
        """[(id, score)] via the attached retriever, or None if detached."""
        retriever = getattr(self, "_retriever", None)
        if retriever is None:
            return None
        vals, idx = retriever.topk(query_vec, num)
        return [(inverse_ids[int(i)], float(v))
                for v, i in zip(vals, idx) if i >= 0]
