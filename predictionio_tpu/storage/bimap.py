"""Bidirectional id <-> dense-index maps.

Analog of the reference's ``BiMap``/``EntityMap`` (reference: data/src/main/
scala/io/prediction/data/storage/BiMap.scala:25-164, EntityMap.scala) — the
reindexing step every factorization algorithm needs: string entity ids to
contiguous integer indices that address rows of TPU-resident factor matrices.

TPU-first design note: instead of the reference's RDD-based constructors
(``BiMap.stringInt(rdd)``), construction here is vectorized over numpy arrays
(``BiMap.from_array``) so a million-id vocabulary builds in one
``np.unique`` call and the forward map lives as a hash map on host while the
inverse map is a dense numpy array usable directly for device gathers.
"""

from __future__ import annotations

from typing import Generic, Iterable, Iterator, Mapping, Sequence, TypeVar

import numpy as np

K = TypeVar("K")
V = TypeVar("V")

__all__ = ["BiMap", "string_int_bimap"]


class BiMap(Generic[K, V]):
    """Immutable bidirectional map. ``apply``/``[]`` maps forward;
    ``inverse`` gives the reversed map. Raises ``KeyError`` on misses,
    like the reference's ``BiMap.apply`` (BiMap.scala:38).
    """

    __slots__ = ("_m", "_i")

    def __init__(self, m: Mapping[K, V], _inverse: "BiMap[V, K] | None" = None):
        self._m = dict(m)
        if len(self._m) != len(set(self._m.values())):
            raise ValueError("BiMap values must be unique")
        self._i = _inverse

    @property
    def inverse(self) -> "BiMap[V, K]":
        if self._i is None:
            self._i = BiMap({v: k for k, v in self._m.items()}, _inverse=self)
        return self._i

    def __getitem__(self, k: K) -> V:
        return self._m[k]

    def get(self, k: K, default: V | None = None) -> V | None:
        return self._m.get(k, default)

    def get_or_else(self, k: K, default: V) -> V:
        return self._m.get(k, default)

    def contains(self, k: K) -> bool:
        return k in self._m

    def __contains__(self, k: object) -> bool:
        return k in self._m

    def __len__(self) -> int:
        return len(self._m)

    def __iter__(self) -> Iterator[K]:
        return iter(self._m)

    def keys(self):
        return self._m.keys()

    def values(self):
        return self._m.values()

    def items(self):
        return self._m.items()

    def to_dict(self) -> dict[K, V]:
        return dict(self._m)

    def take(self, n: int) -> "BiMap[K, V]":
        return BiMap(dict(list(self._m.items())[:n]))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BiMap) and self._m == other._m

    def __repr__(self) -> str:
        return f"BiMap({len(self._m)} entries)"

    # -- vectorized construction / lookup (the TPU path) ------------------
    @staticmethod
    def from_iterable(keys: Iterable[K]) -> "BiMap[K, int]":
        """Dense 0..n-1 indexing of distinct keys, first-seen order is not
        guaranteed (sorted for determinism, matching ``np.unique``)."""
        uniq = sorted(set(keys))  # type: ignore[type-var]
        return BiMap({k: i for i, k in enumerate(uniq)})

    @staticmethod
    def from_array(keys: np.ndarray) -> tuple["BiMap[object, int]", np.ndarray]:
        """Vectorized: returns (bimap, indices) where ``indices[j]`` is the
        dense index of ``keys[j]``. One ``np.unique`` pass — the analog of
        the reference's ``stringInt(rdd)`` (BiMap.scala:116-126) without a
        shuffle."""
        uniq, inv = np.unique(keys, return_inverse=True)
        bm = BiMap({k.item() if hasattr(k, "item") else k: i for i, k in enumerate(uniq)})
        return bm, inv.astype(np.int32)

    def map_array(self, keys: Sequence[K], default: int = -1) -> np.ndarray:
        """Map a batch of keys to indices; unseen keys -> ``default``."""
        return np.asarray([self._m.get(k, default) for k in keys], dtype=np.int32)

    def inverse_array(self) -> np.ndarray:
        """Dense inverse for int-valued BiMaps: array ``a`` with
        ``a[index] = key position``; only valid when values are 0..n-1."""
        n = len(self._m)
        keys = list(self._m.keys())
        vals = np.asarray(list(self._m.values()))
        if vals.min(initial=0) != 0 or vals.max(initial=-1) != n - 1:
            raise ValueError("inverse_array requires dense 0..n-1 values")
        out = np.empty(n, dtype=object)
        out[vals] = keys
        return out


def string_int_bimap(keys: Iterable[str]) -> BiMap[str, int]:
    """Reference ``BiMap.stringInt`` (BiMap.scala:72-90)."""
    return BiMap.from_iterable(keys)
