"""Metadata entities and their DAOs.

The reference keeps framework metadata (apps, access keys, channels, engine
manifests/instances, evaluation instances) in Elasticsearch/MongoDB behind
per-entity DAO traits (reference: data/src/main/scala/io/prediction/data/
storage/{Apps,AccessKeys,Channels,EngineManifests,EngineInstances,
EvaluationInstances}.scala). Here a single SQLite database holds all
metadata tables — one file, transactional, zero services; ``:memory:`` for
tests. Entities are frozen dataclasses serialized to/from JSON columns.
"""

from __future__ import annotations

import json
import re
import secrets
import sqlite3
import threading
from dataclasses import asdict, dataclass, field, replace
from datetime import datetime, timezone

from ._sqlite_util import LockedConnection

__all__ = [
    "App", "AccessKey", "Channel", "EngineManifest", "EngineInstance",
    "EvaluationInstance", "Model", "MetadataStore", "CHANNEL_NAME_RE",
]

#: reference Channels.scala:35-39
CHANNEL_NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")


@dataclass(frozen=True)
class App:
    id: int
    name: str
    description: str | None = None


@dataclass(frozen=True)
class AccessKey:
    key: str
    appid: int
    events: tuple[str, ...] = ()  # empty = all events allowed (AccessKeys.scala:27-34)


@dataclass(frozen=True)
class Channel:
    id: int
    name: str
    appid: int

    @staticmethod
    def is_valid_name(s: str) -> bool:
        return bool(CHANNEL_NAME_RE.match(s))


@dataclass(frozen=True)
class EngineManifest:
    """Registered engine build (reference EngineManifests.scala:33-43);
    ``files`` are the engine's code paths (module dirs, not jars)."""
    id: str
    version: str
    name: str
    description: str | None = None
    files: tuple[str, ...] = ()
    engine_factory: str = ""


def _utcnow() -> datetime:
    return datetime.now(timezone.utc)


@dataclass(frozen=True)
class EngineInstance:
    """One training/evaluation run's record (EngineInstances.scala:47-67).
    Status lifecycle: INIT -> TRAINING -> COMPLETED | ABORTED, plus
    ABANDONED for stale-heartbeat orphans flipped by the reaper."""
    id: str = ""
    status: str = "INIT"
    start_time: datetime = field(default_factory=_utcnow)
    end_time: datetime = field(default_factory=_utcnow)
    engine_id: str = ""
    engine_version: str = ""
    engine_variant: str = ""
    engine_factory: str = ""
    evaluator_class: str = ""
    batch: str = ""
    env: dict = field(default_factory=dict)
    backend_conf: dict = field(default_factory=dict)  # reference: sparkConf
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""
    evaluator_params: str = ""
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""
    #: UTC isoformat of the supervisor's last liveness stamp; empty until
    #: the first heartbeat. Lets `pio status` / the reaper tell a live
    #: INIT run from an orphan whose process died.
    last_heartbeat: str = ""
    #: supervised retry attempt currently running (0 = first attempt)
    attempt: int = 0
    #: JSON list of [phase, seconds] pairs from the SUCCESSFUL training
    #: attempt (tracing.phase_times_json) — `pio status` shows where the
    #: run's wall clock went. Empty for pre-telemetry records.
    phase_times: str = ""
    #: JSON list of per-attempt convergence summaries (obs/training
    #: ConvergenceTracker.summaries: final/first loss, iterations run,
    #: mean step seconds, final delta norm) stamped at the final status
    #: flip — `pio status` prints them. Empty for pre-telemetry records.
    convergence: str = ""
    #: JSON map of per-process liveness for elastic multi-host runs:
    #: ``{"<process_id>": {"ts": iso, "attempt": n}}``. Each process of
    #: the run stamps its own entry; ``pio status`` shows all of them and
    #: ``supervisor.check_peer_liveness`` raises ``HostLostError`` when a
    #: peer's goes stale. Empty for single-host / pre-elastic records.
    host_heartbeats: str = ""
    #: JSON tuning leaderboard (workflow/tuning.py TuneResult
    #: .leaderboard_json: per-trial params/score/status/error plus the
    #: winning trial index and metric header) stamped onto the WINNER's
    #: instance by ``run_tune`` — `pio status` and the dashboard's
    #: /tune.json read it. Empty for non-tuned runs.
    tuning: str = ""


@dataclass(frozen=True)
class EvaluationInstance:
    """(EvaluationInstances.scala:38-50)"""
    id: str = ""
    status: str = ""
    start_time: datetime = field(default_factory=_utcnow)
    end_time: datetime = field(default_factory=_utcnow)
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: dict = field(default_factory=dict)
    backend_conf: dict = field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclass(frozen=True)
class Model:
    """Serialized model blob keyed by engine-instance id (Models.scala:30).
    ``checksum`` is ``"sha256:<hex>"`` over ``models``; empty for blobs
    written before integrity tracking (verification skips those)."""
    id: str
    models: bytes
    checksum: str = ""

    @staticmethod
    def compute_checksum(blob: bytes) -> str:
        import hashlib

        return "sha256:" + hashlib.sha256(blob).hexdigest()


_DT_FIELDS = {"start_time", "end_time"}


def _utc_sort_key(t: datetime) -> str:
    """Normalized-UTC isoformat for the indexed start_time columns, so
    lexicographic ORDER BY matches chronological order across offsets."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t.astimezone(timezone.utc).isoformat()


def _ser(obj) -> str:
    d = asdict(obj)
    for k in _DT_FIELDS & d.keys():
        d[k] = d[k].isoformat()
    return json.dumps(d)


def _deser(cls, s: str):
    d = json.loads(s)
    for k in _DT_FIELDS & d.keys():
        d[k] = datetime.fromisoformat(d[k])
    for k, v in list(d.items()):
        if isinstance(v, list):
            d[k] = tuple(v)
    return cls(**d)


class MetadataStore:
    """All metadata DAOs over one SQLite database.

    JSON-document tables with a few indexed columns — the same shape as the
    reference's ES documents (e.g. ESEngineInstances.scala:40-90) without
    the cluster.
    """

    def __init__(self, path: str = ":memory:"):
        # A plain :memory: database is private to one connection, so in-memory
        # mode shares a single serialized connection across threads (sqlite3
        # is built in serialized threading mode; our writes additionally hold
        # self._lock so transactions never interleave). File mode uses
        # per-thread connections + WAL.
        self._memory = path == ":memory:"
        self._path = path
        self._local = threading.local()
        self._lock = threading.RLock()
        self._shared = LockedConnection(path, self._lock) if self._memory else None
        self._all_conns: list = []
        self._closed = False
        self._init_schema()

    def _conn(self) -> sqlite3.Connection:
        if self._closed:
            raise RuntimeError(f"{type(self).__name__} is closed")
        if self._shared is not None:
            return self._shared
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path, timeout=30.0)
            with self._lock:
                self._all_conns.append(conn)
            conn.execute("PRAGMA journal_mode=WAL")
            self._local.conn = conn
        return conn

    def _init_schema(self) -> None:
        c = self._conn()
        with self._lock:
            c.executescript(
                """
                CREATE TABLE IF NOT EXISTS apps (
                  id INTEGER PRIMARY KEY AUTOINCREMENT, name TEXT UNIQUE, doc TEXT);
                CREATE TABLE IF NOT EXISTS access_keys (
                  key TEXT PRIMARY KEY, appid INTEGER, doc TEXT);
                CREATE TABLE IF NOT EXISTS channels (
                  id INTEGER PRIMARY KEY AUTOINCREMENT, appid INTEGER, name TEXT, doc TEXT,
                  UNIQUE(appid, name));
                CREATE TABLE IF NOT EXISTS engine_manifests (
                  id TEXT, version TEXT, doc TEXT, PRIMARY KEY (id, version));
                CREATE TABLE IF NOT EXISTS engine_instances (
                  id TEXT PRIMARY KEY, status TEXT, engine_id TEXT,
                  engine_version TEXT, engine_variant TEXT, start_time TEXT,
                  last_heartbeat TEXT DEFAULT '', attempt INTEGER DEFAULT 0,
                  doc TEXT);
                CREATE TABLE IF NOT EXISTS evaluation_instances (
                  id TEXT PRIMARY KEY, status TEXT, start_time TEXT, doc TEXT);
                CREATE TABLE IF NOT EXISTS models (
                  id TEXT PRIMARY KEY, blob BLOB, checksum TEXT DEFAULT '');
                CREATE TABLE IF NOT EXISTS sequences (
                  name TEXT PRIMARY KEY, value INTEGER);
                """
            )
            # Databases created before heartbeat/attempt/checksum existed
            # migrate in place (ALTER TABLE ADD COLUMN is cheap and
            # idempotent via the PRAGMA check).
            self._add_missing_column(c, "engine_instances",
                                     "last_heartbeat", "TEXT DEFAULT ''")
            self._add_missing_column(c, "engine_instances",
                                     "attempt", "INTEGER DEFAULT 0")
            self._add_missing_column(c, "models", "checksum", "TEXT DEFAULT ''")
            c.commit()

    @staticmethod
    def _add_missing_column(c, table: str, column: str, decl: str) -> None:
        cols = {r[1] for r in c.execute(f"PRAGMA table_info({table})")}
        if column not in cols:
            c.execute(f"ALTER TABLE {table} ADD COLUMN {column} {decl}")

    def close(self) -> None:
        self._closed = True
        with self._lock:
            for conn in self._all_conns:
                try:
                    conn.close()
                except sqlite3.ProgrammingError:
                    pass  # a conn owned by a live worker thread; dropped at exit
            self._all_conns.clear()
        self._local.conn = None
        if self._shared is not None:
            self._shared.close()
            self._shared = None

    # -- sequences (ESSequences analog) -----------------------------------
    def next_id(self, name: str) -> int:
        c = self._conn()
        with self._lock:
            c.execute(
                "INSERT INTO sequences VALUES (?, 1) "
                "ON CONFLICT(name) DO UPDATE SET value = value + 1",
                (name,),
            )
            (v,) = c.execute("SELECT value FROM sequences WHERE name=?", (name,)).fetchone()
            c.commit()
            return int(v)

    # -- apps (Apps.scala:41-70) ------------------------------------------
    def app_insert(self, name: str, description: str | None = None) -> App | None:
        c = self._conn()
        with self._lock:
            try:
                cur = c.execute(
                    "INSERT INTO apps (name, doc) VALUES (?, ?)", (name, "")
                )
            except sqlite3.IntegrityError:
                return None
            app = App(id=cur.lastrowid, name=name, description=description)
            c.execute("UPDATE apps SET doc=? WHERE id=?", (_ser(app), app.id))
            c.commit()
            return app

    def app_get(self, app_id: int) -> App | None:
        row = self._conn().execute("SELECT doc FROM apps WHERE id=?", (app_id,)).fetchone()
        return _deser(App, row[0]) if row else None

    def app_get_by_name(self, name: str) -> App | None:
        row = self._conn().execute("SELECT doc FROM apps WHERE name=?", (name,)).fetchone()
        return _deser(App, row[0]) if row else None

    def app_get_all(self) -> list[App]:
        return [_deser(App, r[0]) for r in self._conn().execute("SELECT doc FROM apps ORDER BY id")]

    def app_update(self, app: App) -> bool:
        c = self._conn()
        with self._lock:
            try:
                cur = c.execute(
                    "UPDATE apps SET name=?, doc=? WHERE id=?",
                    (app.name, _ser(app), app.id),
                )
            except sqlite3.IntegrityError:  # rename onto an existing name
                return False
            c.commit()
            return cur.rowcount > 0

    def app_delete(self, app_id: int) -> bool:
        c = self._conn()
        with self._lock:
            cur = c.execute("DELETE FROM apps WHERE id=?", (app_id,))
            c.commit()
            return cur.rowcount > 0

    # -- access keys (AccessKeys.scala:37-77) -----------------------------
    def access_key_insert(
        self, appid: int, events: tuple[str, ...] = (), key: str | None = None
    ) -> AccessKey | None:
        """None on duplicate caller-chosen key (same conflict contract as
        app_insert/channel_insert)."""
        ak = AccessKey(key=key or secrets.token_urlsafe(32), appid=appid, events=tuple(events))
        c = self._conn()
        with self._lock:
            try:
                c.execute(
                    "INSERT INTO access_keys VALUES (?, ?, ?)", (ak.key, appid, _ser(ak))
                )
            except sqlite3.IntegrityError:
                return None
            c.commit()
        return ak

    def access_key_get(self, key: str) -> AccessKey | None:
        row = self._conn().execute("SELECT doc FROM access_keys WHERE key=?", (key,)).fetchone()
        return _deser(AccessKey, row[0]) if row else None

    def access_key_get_all(self) -> list[AccessKey]:
        return [_deser(AccessKey, r[0]) for r in self._conn().execute("SELECT doc FROM access_keys")]

    def access_key_get_by_appid(self, appid: int) -> list[AccessKey]:
        return [
            _deser(AccessKey, r[0])
            for r in self._conn().execute("SELECT doc FROM access_keys WHERE appid=?", (appid,))
        ]

    def access_key_delete(self, key: str) -> bool:
        c = self._conn()
        with self._lock:
            cur = c.execute("DELETE FROM access_keys WHERE key=?", (key,))
            c.commit()
            return cur.rowcount > 0

    # -- channels (Channels.scala:44-71) ----------------------------------
    def channel_insert(self, appid: int, name: str) -> Channel | None:
        if not Channel.is_valid_name(name):
            return None
        c = self._conn()
        with self._lock:
            try:
                cur = c.execute(
                    "INSERT INTO channels (appid, name, doc) VALUES (?, ?, ?)",
                    (appid, name, ""),
                )
            except sqlite3.IntegrityError:
                return None
            ch = Channel(id=cur.lastrowid, name=name, appid=appid)
            c.execute("UPDATE channels SET doc=? WHERE id=?", (_ser(ch), ch.id))
            c.commit()
            return ch

    def channel_get(self, channel_id: int) -> Channel | None:
        row = self._conn().execute("SELECT doc FROM channels WHERE id=?", (channel_id,)).fetchone()
        return _deser(Channel, row[0]) if row else None

    def channel_get_by_appid(self, appid: int) -> list[Channel]:
        return [
            _deser(Channel, r[0])
            for r in self._conn().execute(
                "SELECT doc FROM channels WHERE appid=? ORDER BY id", (appid,)
            )
        ]

    def channel_delete(self, channel_id: int) -> bool:
        c = self._conn()
        with self._lock:
            cur = c.execute("DELETE FROM channels WHERE id=?", (channel_id,))
            c.commit()
            return cur.rowcount > 0

    # -- engine manifests (EngineManifests.scala:47-77) -------------------
    def engine_manifest_insert(self, m: EngineManifest) -> None:
        c = self._conn()
        with self._lock:
            c.execute(
                "INSERT OR REPLACE INTO engine_manifests VALUES (?, ?, ?)",
                (m.id, m.version, _ser(m)),
            )
            c.commit()

    def engine_manifest_get(self, id: str, version: str) -> EngineManifest | None:
        row = self._conn().execute(
            "SELECT doc FROM engine_manifests WHERE id=? AND version=?", (id, version)
        ).fetchone()
        return _deser(EngineManifest, row[0]) if row else None

    def engine_manifest_get_all(self) -> list[EngineManifest]:
        return [
            _deser(EngineManifest, r[0])
            for r in self._conn().execute("SELECT doc FROM engine_manifests")
        ]

    def engine_manifest_delete(self, id: str, version: str) -> bool:
        c = self._conn()
        with self._lock:
            cur = c.execute(
                "DELETE FROM engine_manifests WHERE id=? AND version=?", (id, version)
            )
            c.commit()
            return cur.rowcount > 0

    # -- engine instances (EngineInstances.scala:72-130) ------------------
    def engine_instance_insert(self, i: EngineInstance) -> str:
        if not i.id:
            i = replace(i, id=f"ei_{self.next_id('engine_instances'):08d}")
        c = self._conn()
        with self._lock:
            c.execute(
                "INSERT OR REPLACE INTO engine_instances "
                "(id, status, engine_id, engine_version, engine_variant, "
                " start_time, last_heartbeat, attempt, doc) "
                "VALUES (?,?,?,?,?,?,?,?,?)",
                (i.id, i.status, i.engine_id, i.engine_version, i.engine_variant,
                 _utc_sort_key(i.start_time), i.last_heartbeat, i.attempt,
                 _ser(i)),
            )
            c.commit()
        return i.id

    def engine_instance_get(self, id: str) -> EngineInstance | None:
        row = self._conn().execute(
            "SELECT doc FROM engine_instances WHERE id=?", (id,)
        ).fetchone()
        return _deser(EngineInstance, row[0]) if row else None

    def engine_instance_get_all(self) -> list[EngineInstance]:
        return [
            _deser(EngineInstance, r[0])
            for r in self._conn().execute("SELECT doc FROM engine_instances")
        ]

    def engine_instance_get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        """Completed instances, latest first (EngineInstances.scala:100-110)."""
        rows = self._conn().execute(
            "SELECT doc FROM engine_instances WHERE status='COMPLETED' AND "
            "engine_id=? AND engine_version=? AND engine_variant=? "
            "ORDER BY start_time DESC",
            (engine_id, engine_version, engine_variant),
        )
        return [_deser(EngineInstance, r[0]) for r in rows]

    def engine_instance_get_by_status(self, status: str) -> list[EngineInstance]:
        """All instances with ``status``, latest first — the reaper's scan
        (status='INIT') and `pio status`'s live-run listing."""
        rows = self._conn().execute(
            "SELECT doc FROM engine_instances WHERE status=? "
            "ORDER BY start_time DESC",
            (status,),
        )
        return [_deser(EngineInstance, r[0]) for r in rows]

    def engine_instance_get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        done = self.engine_instance_get_completed(engine_id, engine_version, engine_variant)
        return done[0] if done else None

    def engine_instance_update(self, i: EngineInstance) -> None:
        self.engine_instance_insert(i)

    def engine_instance_delete(self, id: str) -> bool:
        c = self._conn()
        with self._lock:
            cur = c.execute("DELETE FROM engine_instances WHERE id=?", (id,))
            c.commit()
            return cur.rowcount > 0

    # -- evaluation instances (EvaluationInstances.scala:55-90) -----------
    def evaluation_instance_insert(self, i: EvaluationInstance) -> str:
        if not i.id:
            i = replace(i, id=f"ev_{self.next_id('evaluation_instances'):08d}")
        c = self._conn()
        with self._lock:
            c.execute(
                "INSERT OR REPLACE INTO evaluation_instances VALUES (?,?,?,?)",
                (i.id, i.status, _utc_sort_key(i.start_time), _ser(i)),
            )
            c.commit()
        return i.id

    def evaluation_instance_get(self, id: str) -> EvaluationInstance | None:
        row = self._conn().execute(
            "SELECT doc FROM evaluation_instances WHERE id=?", (id,)
        ).fetchone()
        return _deser(EvaluationInstance, row[0]) if row else None

    def evaluation_instance_get_all(self) -> list[EvaluationInstance]:
        return [
            _deser(EvaluationInstance, r[0])
            for r in self._conn().execute("SELECT doc FROM evaluation_instances")
        ]

    def evaluation_instance_get_completed(self) -> list[EvaluationInstance]:
        rows = self._conn().execute(
            "SELECT doc FROM evaluation_instances WHERE status='EVALCOMPLETED' "
            "ORDER BY start_time DESC"
        )
        return [_deser(EvaluationInstance, r[0]) for r in rows]

    def evaluation_instance_update(self, i: EvaluationInstance) -> None:
        self.evaluation_instance_insert(i)

    def evaluation_instance_delete(self, id: str) -> bool:
        c = self._conn()
        with self._lock:
            cur = c.execute("DELETE FROM evaluation_instances WHERE id=?", (id,))
            c.commit()
            return cur.rowcount > 0

    # -- model blobs (Models.scala:36-50) ---------------------------------
    def model_insert(self, m: Model) -> None:
        c = self._conn()
        with self._lock:
            c.execute(
                "INSERT OR REPLACE INTO models (id, blob, checksum) "
                "VALUES (?, ?, ?)",
                (m.id, m.models, m.checksum),
            )
            c.commit()

    def model_get(self, id: str) -> Model | None:
        row = self._conn().execute(
            "SELECT blob, checksum FROM models WHERE id=?", (id,)
        ).fetchone()
        return Model(id=id, models=row[0], checksum=row[1] or "") if row else None

    def model_delete(self, id: str) -> bool:
        c = self._conn()
        with self._lock:
            cur = c.execute("DELETE FROM models WHERE id=?", (id,))
            c.commit()
            return cur.rowcount > 0
