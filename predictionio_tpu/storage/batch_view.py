"""Deprecated batch-view API kept for engine-code compatibility.

Analog of the reference's pre-0.9.2 ``LBatchView``/``PBatchView`` classes
(reference: data/src/main/scala/io/prediction/data/view/LBatchView.scala:
28-134, PBatchView.scala:34), which the reference itself ships
``@deprecated`` in favor of the event store + aggregation API. Provided so
ported engine code keeps running; new code should call
``EventStore.find``/``aggregate_properties`` directly.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Callable

from ..annotation import deprecated
from .aggregate import aggregate_properties
from .datamap import PropertyMap
from .event import Event
from .events_base import EventQuery
from .registry import Storage

__all__ = ["LBatchView", "PBatchView"]


class _BatchViewBase:
    def __init__(self, app_id: int, start_time: datetime | None = None,
                 until_time: datetime | None = None,
                 channel_id: int | None = None):
        self.app_id = app_id
        self.start_time = start_time
        self.until_time = until_time
        self.channel_id = channel_id

    def _events(self, entity_type: str | None = None) -> list[Event]:
        return list(Storage.get_events().find(EventQuery(
            app_id=self.app_id, channel_id=self.channel_id,
            start_time=self.start_time, until_time=self.until_time,
            entity_type=entity_type,
        )))

    # LBatchView.aggregateProperties (LBatchView.scala:94-107)
    def aggregate_properties(self, entity_type: str) -> dict[str, PropertyMap]:
        # entity_type filters at the store level, not over the full app
        return aggregate_properties(self._events(entity_type))

    # LBatchView.events + filtering convenience (LBatchView.scala:44-77)
    def events(self, predicate: Callable[[Event], bool] | None = None) -> list[Event]:
        evs = self._events()
        return [e for e in evs if predicate(e)] if predicate else evs

    # LBatchView.aggregateByEntityOrdered (LBatchView.scala:109-134)
    def aggregate_by_entity_ordered(
        self, predicate: Callable[[Event], bool],
        init: Any, op: Callable[[Any, Event], Any],
    ) -> dict[str, Any]:
        per_entity: dict[str, list[Event]] = {}
        for e in self.events(predicate):
            per_entity.setdefault(e.entity_id, []).append(e)
        out = {}
        for eid, evs in per_entity.items():
            acc = init
            for e in sorted(evs, key=lambda e: e.event_time):
                acc = op(acc, e)
            out[eid] = acc
        return out


@deprecated("use EventStore.find / aggregate_properties")
class LBatchView(_BatchViewBase):
    """Local (iterator-backed) batch view."""


@deprecated("use EventStore.find_frame / aggregate_properties")
class PBatchView(_BatchViewBase):
    """'Parallel' batch view — in the TPU build both views read the same
    columnar store; this alias mirrors the reference's P/L split."""
