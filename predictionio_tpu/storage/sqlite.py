"""SQLite event store backend — the durable single-node EVENTDATA store.

Replaces the role of the reference's HBase event backend (reference:
data/src/main/scala/io/prediction/data/storage/hbase/HBEventsUtil.scala,
HBLEvents.scala): one table per (app, channel) named
``events_<appId>[_<channelId>]`` like the reference's
``pio_event:events_<appId>_<channelId>`` naming (HBEventsUtil.scala:51-58),
rows keyed by a time-ordered synthetic key, with indexed columns for the
standard filters. Properties ride as JSON text.

SQLite (WAL mode) gives durable multi-reader/single-writer semantics in one
file with zero external services — the right call for a single host; the
storage registry lets a real distributed backend plug in behind the same
``EventBackend`` SPI without touching callers.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import uuid
from datetime import datetime, timezone
from typing import Iterator, Sequence

import numpy as np

from ._sqlite_util import LockedConnection
from .datamap import DataMap
from .event import Event
from .frame import EventFrame
from .events_base import ANY, EventBackend, EventQuery, StorageError, TableNotInitialized

__all__ = ["SQLiteEvents"]

_SCHEMA = """
CREATE TABLE IF NOT EXISTS {table} (
  event_id TEXT PRIMARY KEY,
  event TEXT NOT NULL,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  target_entity_type TEXT,
  target_entity_id TEXT,
  properties TEXT NOT NULL,
  event_time REAL NOT NULL,
  tags TEXT NOT NULL,
  pr_id TEXT,
  creation_time REAL NOT NULL,
  seq INTEGER
);
CREATE INDEX IF NOT EXISTS {table}_time ON {table} (event_time, seq);
CREATE INDEX IF NOT EXISTS {table}_entity ON {table} (entity_type, entity_id, event_time);
"""


def _table_name(app_id: int, channel_id: int | None) -> str:
    if channel_id is None:
        return f"events_{app_id}"
    return f"events_{app_id}_{channel_id}"


class SQLiteEvents(EventBackend):
    def __init__(self, config: dict | None = None):
        config = config or {}
        path = config.get("path", ":memory:")
        # see MetadataStore._conn: in-memory mode = one serialized connection
        self._memory = path == ":memory:"
        self._path = path
        self._local = threading.local()
        self._lock = threading.RLock()
        self._shared = LockedConnection(path, self._lock) if self._memory else None
        self._all_conns: list = []
        self._closed = False
        self._known_tables: set[str] = set()
        self._seq = 0

    def _raise_if_closed(self) -> None:
        if self._closed:
            raise RuntimeError(f"{type(self).__name__} is closed")

    def _conn(self) -> sqlite3.Connection:
        self._raise_if_closed()
        if self._shared is not None:
            return self._shared
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # check_same_thread=False so close() can REALLY close every
            # thread's connection (each conn is still used by one thread;
            # writes additionally serialize under self._lock) — otherwise
            # worker conns dangle open past close() and leak the file
            # handle until thread exit
            conn = sqlite3.connect(self._path, timeout=30.0,
                                   check_same_thread=False)
            with self._lock:
                if self._closed:  # close() raced us: do not leak a conn
                    conn.close()
                    self._raise_if_closed()
                self._all_conns.append(conn)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def _ensure_table(self, app_id: int, channel_id: int | None, create: bool) -> str:
        table = _table_name(app_id, channel_id)
        if table in self._known_tables:
            return table
        conn = self._conn()
        row = conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?", (table,)
        ).fetchone()
        if row is None:
            if not create:
                raise TableNotInitialized(
                    f"events table for app {app_id} channel {channel_id} "
                    "not initialized (run init_app / `pio app new`)"
                )
            with self._lock:
                conn.executescript(_SCHEMA.format(table=table))
                conn.commit()
        else:
            # resume the tie-break sequence past any rows already on disk
            (mx,) = conn.execute(f"SELECT COALESCE(MAX(seq), 0) FROM {table}").fetchone()
            with self._lock:
                self._seq = max(self._seq, int(mx))
        self._known_tables.add(table)
        return table

    # -- lifecycle --------------------------------------------------------
    def init_app(self, app_id: int, channel_id: int | None = None) -> bool:
        self._ensure_table(app_id, channel_id, create=True)
        return True

    def remove_app(self, app_id: int, channel_id: int | None = None) -> bool:
        table = _table_name(app_id, channel_id)
        conn = self._conn()
        with self._lock:
            conn.execute(f"DROP TABLE IF EXISTS {table}")
            conn.commit()
            self._known_tables.discard(table)
        return True

    def close(self) -> None:
        """Close every thread's connection. Post-close use on ANY thread
        — including a find() iterator already mid-flight — surfaces as
        the "is closed" RuntimeError via the ``_closed`` guard, never a
        raw ``sqlite3.ProgrammingError`` over a dangling handle."""
        with self._lock:
            self._closed = True
            for conn in self._all_conns:
                try:
                    conn.close()
                except sqlite3.ProgrammingError:
                    pass  # mid-statement on another thread; GC'd at exit
            self._all_conns.clear()
            self._local.conn = None
            if self._shared is not None:
                self._shared.close()
                self._shared = None

    # -- writes -----------------------------------------------------------
    def _row(self, e: Event) -> tuple:
        return (
            e.event_id,
            e.event,
            e.entity_type,
            e.entity_id,
            e.target_entity_type,
            e.target_entity_id,
            e.properties.to_json(),
            e.event_time.timestamp(),
            "[]" if not e.tags else json.dumps(list(e.tags)),
            e.pr_id,
            e.creation_time.timestamp(),
        )

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        table = self._ensure_table(app_id, channel_id, create=True)
        e = event if event.event_id else event.with_id(uuid.uuid4().hex)
        conn = self._conn()
        with self._lock:
            self._seq += 1
            conn.execute(
                f"INSERT OR REPLACE INTO {table} VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
                self._row(e) + (self._seq,),
            )
            conn.commit()
        return e.event_id  # type: ignore[return-value]

    BATCH_ATOMIC = True  # one executemany inside one transaction

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        table = self._ensure_table(app_id, channel_id, create=True)
        withids = [e if e.event_id else e.with_id(uuid.uuid4().hex) for e in events]
        conn = self._conn()
        with self._lock:
            rows = []
            for e in withids:
                self._seq += 1
                rows.append(self._row(e) + (self._seq,))
            try:
                conn.executemany(
                    f"INSERT OR REPLACE INTO {table} VALUES (?,?,?,?,?,?,?,?,?,?,?,?)", rows
                )
                conn.commit()
            except sqlite3.Error as e:
                # the BATCH_ATOMIC contract: a failure persists NOTHING.
                # Without the rollback, rows already in the implicit
                # transaction would ride out on the NEXT commit of this
                # (thread-reused) connection; callers also only catch
                # StorageError, not raw sqlite3 errors.
                conn.rollback()
                raise StorageError(f"batch insert failed: {e}") from e
        return [e.event_id for e in withids]  # type: ignore[misc]

    # -- point ops --------------------------------------------------------
    def _from_row(self, row: tuple) -> Event:
        return Event(
            event_id=row[0],
            event=row[1],
            entity_type=row[2],
            entity_id=row[3],
            target_entity_type=row[4],
            target_entity_id=row[5],
            properties=DataMap.from_json(row[6]),
            event_time=datetime.fromtimestamp(row[7], tz=timezone.utc),
            tags=tuple(json.loads(row[8])),
            pr_id=row[9],
            creation_time=datetime.fromtimestamp(row[10], tz=timezone.utc),
        )

    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        table = self._ensure_table(app_id, channel_id, create=False)
        try:
            row = self._conn().execute(
                f"SELECT * FROM {table} WHERE event_id=?", (event_id,)
            ).fetchone()
        except sqlite3.ProgrammingError:
            self._raise_if_closed()  # close() raced us mid-statement
            raise
        return self._from_row(row) if row else None

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        table = self._ensure_table(app_id, channel_id, create=False)
        conn = self._conn()
        with self._lock:
            cur = conn.execute(f"DELETE FROM {table} WHERE event_id=?", (event_id,))
            conn.commit()
            return cur.rowcount > 0

    def remove_before(self, app_id: int, cutoff, channel_id: int | None = None) -> int:
        """Bulk time-windowed trim: one indexed DELETE instead of the
        base class's scan + per-row deletes."""
        table = self._ensure_table(app_id, channel_id, create=False)
        if cutoff.tzinfo is None:
            # naive datetimes are UTC everywhere in this codebase
            # (EventQuery.__post_init__); .timestamp() on a naive value
            # would read it in server-local time instead
            cutoff = cutoff.replace(tzinfo=timezone.utc)
        conn = self._conn()
        with self._lock:
            cur = conn.execute(
                f"DELETE FROM {table} WHERE event_time < ?",
                (cutoff.timestamp(),))
            conn.commit()
            return cur.rowcount

    # -- scans ------------------------------------------------------------
    @staticmethod
    def _where(query: EventQuery) -> tuple[str, list]:
        clauses, params = [], []
        if query.start_time is not None:
            clauses.append("event_time >= ?")
            params.append(query.start_time.timestamp())
        if query.until_time is not None:
            clauses.append("event_time < ?")
            params.append(query.until_time.timestamp())
        if query.entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(query.entity_type)
        if query.entity_id is not None:
            clauses.append("entity_id = ?")
            params.append(query.entity_id)
        if query.event_names is not None:
            clauses.append(
                "event IN (%s)" % ",".join("?" * len(query.event_names))
            )
            params.extend(query.event_names)
        if query.target_entity_type is not ANY:
            if query.target_entity_type is None:
                clauses.append("target_entity_type IS NULL")
            else:
                clauses.append("target_entity_type = ?")
                params.append(query.target_entity_type)
        if query.target_entity_id is not ANY:
            if query.target_entity_id is None:
                clauses.append("target_entity_id IS NULL")
            else:
                clauses.append("target_entity_id = ?")
                params.append(query.target_entity_id)
        return (" WHERE " + " AND ".join(clauses)) if clauses else "", params

    def find(self, query: EventQuery) -> Iterator[Event]:
        table = self._ensure_table(query.app_id, query.channel_id, create=False)
        where, params = self._where(query)
        order = "DESC" if query.reversed else "ASC"
        sql = f"SELECT * FROM {table}{where} ORDER BY event_time {order}, seq {order}"
        if query.limit is not None and query.limit >= 0:
            sql += f" LIMIT {int(query.limit)}"
        # the lazy cursor iterates across yields; close() can land between
        # them, and its intended signal is the _closed RuntimeError — not a
        # raw sqlite3.ProgrammingError off the dead cursor
        try:
            rows = iter(self._conn().execute(sql, params))
        except sqlite3.ProgrammingError:
            self._raise_if_closed()
            raise
        while True:
            try:
                row = next(rows)
            except StopIteration:
                return
            except sqlite3.ProgrammingError:
                self._raise_if_closed()
                raise
            yield self._from_row(row)

    def find_frame(self, query: EventQuery):
        """Columnar scan straight from SQL rows — the training read path
        skips per-event ``Event``/``DataMap`` materialization (measured
        ~4x over the base from_events path at 200k events; this is the
        HBase-scan-to-RDD stage of reference training reads,
        HBPEvents.scala:66-99, as one SELECT into numpy columns)."""
        table = self._ensure_table(query.app_id, query.channel_id,
                                   create=False)
        where, params = self._where(query)
        sql = (f"SELECT event, entity_type, entity_id, target_entity_type, "
               f"target_entity_id, event_time, properties FROM {table}"
               f"{where} ORDER BY event_time ASC, seq ASC")
        rows = self._conn().execute(sql, params).fetchall()
        if not rows:
            empty = np.empty(0, dtype=object)
            return EventFrame(event=empty, entity_type=empty.copy(),
                              entity_id=empty.copy(),
                              target_entity_type=empty.copy(),
                              target_entity_id=empty.copy(),
                              event_time=np.empty(0, dtype=np.float64),
                              properties=[])
        # one C-level transpose instead of 7 assignments per row — the
        # per-row loop was ~half the 200k-event scan cost
        ev_c, et_c, ei_c, tt_c, ti_c, tm_c, pj_c = zip(*rows)
        loads = json.loads
        # bulk imports repeat property shapes; memoizing on the raw JSON
        # string skips most of the parse cost. The dicts are therefore
        # SHARED across rows — EventFrame.properties is a read-only view.
        memo: dict = {}
        pr = []
        for p in pj_c:
            d = memo.get(p)
            if d is None:
                d = loads(p) if p else {}
                memo[p] = d
            pr.append(d)
        return EventFrame(
            event=np.array(ev_c, dtype=object),
            entity_type=np.array(et_c, dtype=object),
            entity_id=np.array(ei_c, dtype=object),
            target_entity_type=np.array(tt_c, dtype=object),
            target_entity_id=np.array(ti_c, dtype=object),
            event_time=np.array(tm_c, dtype=np.float64),
            properties=pr)
