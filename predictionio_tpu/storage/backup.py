"""Disaster recovery for every durable store under ``$PIO_HOME``.

Each store in the system is individually crash-safe — the ingest WAL
(journal.py), the sha256-sidecar blob store (registry.py), sharded
training checkpoints (workflow/checkpoint.py), the durable router state
(workflow/fleet.py) — but none of that survives losing the disk.  This
module is the cross-store recovery layer:

* ``create_backup`` takes a consistent, integrity-verified snapshot of
  ALL durable state: sqlite databases are copied through sqlite3's
  online backup API (never torn under a live server), everything else
  is copied behind a size fence recorded AFTER the database cut, so the
  WAL tail in the backup always covers the window between the database
  snapshot and the fence.  A backup EXISTS only when its CRC-framed
  manifest parses — the PR-8 checkpoint discipline applied store-wide.
  Incremental mode hardlinks files whose (path, size, mtime) or content
  hash matches the previous complete backup.
* ``restore`` rebuilds a fresh ``$PIO_HOME`` from any complete backup:
  re-verifies every checksum first, refuses a non-empty target without
  ``force``, and supports point-in-time recovery by replaying the
  backed-up WAL tail through the same id-keyed exactly-once insert path
  the drain loop uses, optionally up to ``--until <ts|seq>``.
* ``fsck`` audits the cross-store invariants standalone: COMPLETED
  instances' blobs exist and match their checksums, checkpoint
  manifests list only present shards, journal cursors sit at or before
  a validly-framed tail, and the router epoch marker is never behind
  its delta journal.  ``repair=True`` quarantines or clamps rather than
  deletes.
* ``gc_blobs`` deletes model blobs unreferenced by any non-retired
  EngineInstance (ABORTED/ABANDONED attempts otherwise leak blobs
  forever).

Backup and restore share one lockfile (``$PIO_HOME/run/dr.lock``) so
they can never run concurrently against the same home.

Chaos sites: ``backup.copy`` fires before each file enters a backup,
``restore.apply`` before each file is materialized into the target —
both registered in workflow/faults.py SITES.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import sqlite3
import struct
import time
import zlib
from datetime import datetime, timezone
from hashlib import sha256
from pathlib import Path
from typing import Iterable

from ..obs.metrics import METRICS
from ..workflow.faults import FAULTS
from .journal import iter_journal_records

__all__ = [
    "BackupError",
    "DrLocked",
    "RestoreRefused",
    "create_backup",
    "fsck",
    "gc_blobs",
    "list_backups",
    "read_manifest",
    "restore",
    "run_backup_bench",
    "status_lines",
    "verify_backup",
]

# Same on-disk framing as the ingest WAL (journal.py): little-endian
# (payload length, crc32(payload)) ahead of the JSON payload.  A torn or
# bit-flipped manifest fails the CRC and the backup simply does not exist.
_FRAME = struct.Struct("<II")
MANIFEST_NAME = "MANIFEST.bin"
MANIFEST_FORMAT = 1

_BACKUP_RE = re.compile(r"^backup-(\d{8})$")
_SEGMENT_RE = re.compile(r"^journal-(\d{8})\.log$")
_STEP_RE = re.compile(r"^step_(\d+)$")

# Home entries that are rebuildable scratch, not durable state.
_EXCLUDE_TOP = ("backups", "xla_cache", "log", "quarantine")
# sqlite scratch siblings: the online backup API folds the WAL into the
# snapshot, so copying these raw would only tear.
_SQLITE_SCRATCH = ("-wal", "-shm", "-journal")

FSCK_STATE = "fsck-last.json"  # under $PIO_HOME/run/, read by `pio status`

_RETIRED_STATUSES = ("ABORTED", "ABANDONED")

_BACKUP_TOTAL = METRICS.counter(
    "pio_backup_total", "Backups attempted, by terminal status.",
    labelnames=("status",))
_BACKUP_BYTES = METRICS.counter(
    "pio_backup_bytes_total",
    "Bytes physically written into backups (dedup hardlinks excluded).")
_BACKUP_DEDUP = METRICS.counter(
    "pio_backup_dedup_files_total",
    "Files satisfied by hardlinking an identical copy from the previous "
    "complete backup instead of rewriting the bytes.")
_BACKUP_LAST_SEQ = METRICS.gauge(
    "pio_backup_last_success_seq",
    "Sequence number of the newest manifest-complete backup.")
_RESTORE_TOTAL = METRICS.counter(
    "pio_backup_restore_total", "Restores attempted, by terminal status.",
    labelnames=("status",))
_RESTORE_REPLAYED = METRICS.counter(
    "pio_backup_restore_replayed_records_total",
    "WAL records replayed through the id-keyed drain path during restore.")
_VERIFY_FAILURES = METRICS.counter(
    "pio_backup_verify_failures_total",
    "Checksum or manifest failures found while verifying backups.")
_FSCK_RUNS = METRICS.counter(
    "pio_fsck_runs_total", "fsck runs, by verdict.", labelnames=("verdict",))
_FSCK_VIOLATIONS = METRICS.counter(
    "pio_fsck_violations_total",
    "Cross-store integrity violations found by fsck, by invariant.",
    labelnames=("invariant",))
_FSCK_ORPHAN_BLOBS = METRICS.gauge(
    "pio_fsck_orphan_blobs",
    "Model blobs unreferenced by any non-retired engine instance, as of "
    "the last fsck or gc run.")
_FSCK_GC_DELETED = METRICS.counter(
    "pio_fsck_gc_deleted_total",
    "Orphaned model blobs deleted by `pio admin gc --blobs`.")

for _s in ("ok", "error"):
    _BACKUP_TOTAL.labels(status=_s)
for _s in ("ok", "error", "refused", "verify_failed"):
    _RESTORE_TOTAL.labels(status=_s)
for _s in ("clean", "violations"):
    _FSCK_RUNS.labels(verdict=_s)
for _s in ("blob", "checkpoint", "journal", "router_epoch"):
    _FSCK_VIOLATIONS.labels(invariant=_s)
del _s


class BackupError(RuntimeError):
    """Backup/restore could not proceed (corrupt input, no backups, ...)."""


class DrLocked(BackupError):
    """Another backup/restore holds the dr.lock for this home."""


class RestoreRefused(BackupError):
    """Target home is non-empty and ``force`` was not given (CLI exit 2)."""


# --------------------------------------------------------------------------
# small file plumbing (same idiom as workflow/checkpoint.py)

def _fsync_file(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: Path) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sha256_file(path: Path, limit: int | None = None) -> str:
    h = sha256()
    remaining = limit
    with open(path, "rb") as fh:
        while True:
            n = 1 << 20 if remaining is None else min(1 << 20, remaining)
            if n <= 0:
                break
            chunk = fh.read(n)
            if not chunk:
                break
            h.update(chunk)
            if remaining is not None:
                remaining -= len(chunk)
    return h.hexdigest()


def _copy_hashed(src: Path, dst: Path, limit: int | None = None) -> tuple[str, int]:
    """Copy ``src`` (up to ``limit`` bytes — the journal fence) to ``dst``
    via tmp+fsync+rename, hashing the copied bytes in one pass."""
    tmp = dst.with_name(dst.name + ".tmp")
    h = sha256()
    copied = 0
    with open(src, "rb") as rf, open(tmp, "wb") as wf:
        remaining = limit
        while True:
            n = 1 << 20 if remaining is None else min(1 << 20, remaining)
            if n <= 0:
                break
            chunk = rf.read(n)
            if not chunk:
                break
            h.update(chunk)
            wf.write(chunk)
            copied += len(chunk)
            if remaining is not None:
                remaining -= len(chunk)
        wf.flush()
        os.fsync(wf.fileno())
    os.replace(tmp, dst)
    return h.hexdigest(), copied


def _atomic_json(path: Path, obj: dict) -> None:
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(obj, sort_keys=True))
    _fsync_file(tmp)
    os.replace(tmp, path)
    _fsync_dir(path.parent)


def _utcnow_iso() -> str:
    return datetime.now(timezone.utc).isoformat()


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class _DrLock:
    """``$PIO_HOME/run/dr.lock``: backup and restore are mutually
    exclusive per home.  O_EXCL-create with our pid inside; a lock whose
    pid is dead is stale and stolen."""

    def __init__(self, home: Path):
        self.path = Path(home) / "run" / "dr.lock"

    def __enter__(self) -> "_DrLock":
        self.path.parent.mkdir(parents=True, exist_ok=True)
        for _ in range(3):
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                try:
                    pid = int(self.path.read_text().strip() or "0")
                except (OSError, ValueError):
                    pid = 0
                if pid and _pid_alive(pid):
                    raise DrLocked(
                        f"backup/restore already running (pid {pid} holds "
                        f"{self.path}); retry when it finishes")
                try:  # stale: holder died without cleanup
                    self.path.unlink()
                except FileNotFoundError:
                    pass
                continue
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            return self
        raise DrLocked(f"could not acquire {self.path}")

    def __exit__(self, *exc) -> None:
        try:
            self.path.unlink()
        except FileNotFoundError:
            pass


# --------------------------------------------------------------------------
# manifest framing

def _write_manifest(bdir: Path, manifest: dict) -> None:
    payload = json.dumps(manifest, sort_keys=True).encode()
    tmp = bdir / (MANIFEST_NAME + ".tmp")
    with open(tmp, "wb") as fh:
        fh.write(_FRAME.pack(len(payload), zlib.crc32(payload)))
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, bdir / MANIFEST_NAME)
    _fsync_dir(bdir)
    _fsync_dir(bdir.parent)


def read_manifest(bdir: Path) -> dict | None:
    """The backup's manifest, or None if absent/truncated/corrupt — a
    backup without a readable manifest does not exist."""
    try:
        raw = (Path(bdir) / MANIFEST_NAME).read_bytes()
    except OSError:
        return None
    if len(raw) < _FRAME.size:
        return None
    length, crc = _FRAME.unpack(raw[:_FRAME.size])
    payload = raw[_FRAME.size:_FRAME.size + length]
    if len(payload) < length or zlib.crc32(payload) != crc:
        return None
    try:
        m = json.loads(payload)
    except ValueError:
        return None
    if not isinstance(m, dict) or m.get("format") != MANIFEST_FORMAT:
        return None
    return m


def _is_complete(bdir: Path, manifest: dict) -> bool:
    for f in manifest.get("files", ()):
        p = bdir / f["path"]
        try:
            if p.stat().st_size != f["bytes"]:
                return False
        except OSError:
            return False
    return True


def list_backups(root: Path) -> tuple[list[tuple[int, Path, dict]],
                                      list[tuple[int, Path]]]:
    """(complete, partial) backups under ``root``, each oldest-first.
    Complete means the CRC-framed manifest parses AND every listed file
    is present at its recorded size — anything else is a crashed or
    corrupted attempt and is never restored from."""
    root = Path(root)
    complete: list[tuple[int, Path, dict]] = []
    partial: list[tuple[int, Path]] = []
    if not root.is_dir():
        return complete, partial
    for p in sorted(root.iterdir()):
        m = _BACKUP_RE.match(p.name)
        if not m or not p.is_dir():
            continue
        seq = int(m.group(1))
        manifest = read_manifest(p)
        if manifest is not None and _is_complete(p, manifest):
            complete.append((seq, p, manifest))
        else:
            partial.append((seq, p))
    return complete, partial


# --------------------------------------------------------------------------
# backup

def _under(child: Path, parent: Path) -> bool:
    try:
        child.resolve().relative_to(parent.resolve())
        return True
    except ValueError:
        return False


def _scan_home(home: Path, backup_root: Path) -> tuple[list[Path], list[Path]]:
    """(sqlite_dbs, plain_files) of durable state under home.  Scratch
    trees, pidfiles, the dr.lock and the backup root itself are skipped;
    sqlite WAL/SHM siblings are folded by the online backup instead."""
    dbs: list[Path] = []
    plain: list[Path] = []
    if not home.is_dir():
        return dbs, plain
    broot = backup_root.resolve()
    for top in sorted(home.iterdir()):
        if top.name in _EXCLUDE_TOP or top.resolve() == broot:
            continue
        paths = [top] if top.is_file() else sorted(top.rglob("*"))
        for p in paths:
            if not p.is_file() or p.is_symlink():
                continue
            if broot in p.resolve().parents:
                continue  # backups never nest backups
            name = p.name
            if name.endswith(".tmp") or name.endswith(".pid"):
                continue
            if name == "dr.lock" or name == FSCK_STATE:
                continue
            if any(name.endswith(f".db{s}") for s in _SQLITE_SCRATCH):
                continue
            if name.endswith(".db"):
                dbs.append(p)
            else:
                plain.append(p)
    return dbs, plain


def _backup_sqlite(src: Path, dst: Path) -> tuple[str, int]:
    """Snapshot a live sqlite database through the online backup API —
    the copy is transactionally consistent even mid-write."""
    tmp = dst.with_name(dst.name + ".tmp")
    if tmp.exists():
        tmp.unlink()
    try:
        con = sqlite3.connect(str(src))
        try:
            out = sqlite3.connect(str(tmp))
            try:
                con.backup(out)
            finally:
                out.close()
        finally:
            con.close()
    except sqlite3.Error:
        # a .db that is not actually sqlite: plain fenced copy instead
        try:
            tmp.unlink()
        except FileNotFoundError:
            pass
        return _copy_hashed(src, dst, limit=src.stat().st_size)
    _fsync_file(tmp)
    os.replace(tmp, dst)
    digest = _sha256_file(dst)
    return digest, dst.stat().st_size


def create_backup(home: str | os.PathLike | None = None, *,
                  backup_dir: str | os.PathLike | None = None,
                  keep: int = 5, mode: str = "incremental",
                  journal_dir: str | os.PathLike | None = None,
                  checkpoint_dir: str | os.PathLike | None = None) -> dict:
    """Take one manifest-committed snapshot of all durable state.

    Ordering is the consistency argument: sqlite databases are cut
    first (online backup API), then every other file's size is fenced
    at a single pass and copied up to that fence — so the WAL tail in
    the snapshot strictly covers the window after the database cut, and
    replaying it at restore time (id-keyed, idempotent) closes the gap.
    """
    from .registry import Storage
    home = Path(home) if home is not None else Path(Storage.home())
    root = Path(backup_dir) if backup_dir is not None else home / "backups"
    if mode not in ("incremental", "full"):
        raise BackupError(f"unknown backup mode {mode!r}")
    t0 = time.monotonic()
    with _DrLock(home):
        root.mkdir(parents=True, exist_ok=True)
        complete, partial = list_backups(root)
        all_seqs = [s for s, *_ in complete] + [s for s, _ in partial]
        seq = (max(all_seqs) + 1) if all_seqs else 1
        prev_dir: Path | None = None
        prev_files: dict[str, dict] = {}
        if mode == "incremental" and complete:
            _, prev_dir, prev_manifest = complete[-1]
            prev_files = {f["path"]: f for f in prev_manifest["files"]}
        bdir = root / f"backup-{seq:08d}"
        bdir.mkdir()
        try:
            report = _run_backup(
                home, bdir, seq, mode, prev_dir, prev_files,
                journal_dir=Path(journal_dir) if journal_dir else None,
                checkpoint_dir=Path(checkpoint_dir) if checkpoint_dir else None)
        except BaseException:
            _BACKUP_TOTAL.labels(status="error").inc()
            raise
        # retention: count only manifest-complete backups; crashed
        # partials older than this one are swept too.  Hardlinked
        # inodes stay alive in newer backups across the prune.
        complete, partial = list_backups(root)
        for s, p in partial:
            if s < seq:
                shutil.rmtree(p, ignore_errors=True)
        if keep > 0 and len(complete) > keep:
            for s, p, _m in complete[:len(complete) - keep]:
                shutil.rmtree(p, ignore_errors=True)
        _BACKUP_TOTAL.labels(status="ok").inc()
        _BACKUP_LAST_SEQ.set(seq)
        report["durationS"] = round(time.monotonic() - t0, 3)
        report["kept"] = min(len(complete), keep) if keep > 0 else len(complete)
        return report


def _run_backup(home: Path, bdir: Path, seq: int, mode: str,
                prev_dir: Path | None, prev_files: dict[str, dict], *,
                journal_dir: Path | None,
                checkpoint_dir: Path | None) -> dict:
    files: list[dict] = []
    bytes_written = 0
    deduped = 0

    def record(rel: str, digest: str, size: int, mtime_ns: int,
               kind: str, dedup: bool) -> None:
        files.append({"path": rel, "sha256": digest, "bytes": size,
                      "mtimeNs": mtime_ns, "kind": kind, "dedup": dedup})

    db_paths, plain_paths = _scan_home(home, bdir.parent)
    extra: list[tuple[str, Path, Path]] = []  # (prefix, root, file)
    for prefix, d in (("journal", journal_dir), ("checkpoints", checkpoint_dir)):
        if d is None or _under(d, home):
            continue  # under home → already in the home walk
        if d.is_dir():
            for p in sorted(d.rglob("*")):
                if p.is_file() and not p.is_symlink() \
                        and not p.name.endswith(".tmp"):
                    extra.append((prefix, d, p))

    # phase 1: database cut (online backup — never torn)
    for src in db_paths:
        rel = "home/" + src.relative_to(home).as_posix()
        FAULTS.fire("backup.copy")
        dst = bdir / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        digest, size = _backup_sqlite(src, dst)
        bytes_written += size
        record(rel, digest, size, dst.stat().st_mtime_ns, "sqlite", False)

    # phase 2: fence — one stat pass AFTER the database cut.  Append-only
    # files (WAL segments) are copied only up to this size so the
    # snapshot is a consistent cut; everything past it belongs to the
    # next backup.
    fenced: list[tuple[str, Path, int, int]] = []
    for src in plain_paths:
        try:
            st = src.stat()
        except OSError:
            continue  # vanished mid-scan (GC'd segment): not durable state
        fenced.append(("home/" + src.relative_to(home).as_posix(),
                       src, st.st_size, st.st_mtime_ns))
    for prefix, d, src in extra:
        try:
            st = src.stat()
        except OSError:
            continue
        fenced.append((f"{prefix}/" + src.relative_to(d).as_posix(),
                       src, st.st_size, st.st_mtime_ns))

    # phase 3: copy behind the fence, hardlinking unchanged files from
    # the previous complete backup
    for rel, src, size, mtime_ns in fenced:
        FAULTS.fire("backup.copy")
        dst = bdir / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        prev = prev_files.get(rel)
        if prev is not None and prev_dir is not None \
                and prev["bytes"] == size and prev.get("mtimeNs") == mtime_ns:
            try:
                os.link(prev_dir / rel, dst)
                deduped += 1
                record(rel, prev["sha256"], size, mtime_ns, "file", True)
                continue
            except OSError:
                pass  # cross-device or pruned: fall through to a copy
        try:
            digest, copied = _copy_hashed(src, dst, limit=size)
        except FileNotFoundError:
            continue  # vanished between fence and copy
        if prev is not None and prev_dir is not None \
                and prev["sha256"] == digest:
            # content unchanged, only mtime moved (resealed segment):
            # swap the fresh copy for a hardlink so retention dedups it
            try:
                os.link(prev_dir / rel, dst.with_name(dst.name + ".lnk"))
                os.replace(dst.with_name(dst.name + ".lnk"), dst)
                deduped += 1
                record(rel, digest, copied, mtime_ns, "file", True)
                continue
            except OSError:
                pass
        bytes_written += copied
        record(rel, digest, copied, mtime_ns, "file", False)

    manifest = {
        "format": MANIFEST_FORMAT,
        "seq": seq,
        "createdAt": _utcnow_iso(),
        "mode": mode,
        "basedOn": int(_BACKUP_RE.match(prev_dir.name).group(1))
                   if prev_dir is not None else None,
        "roots": {"home": str(home),
                  "journal": str(journal_dir) if journal_dir else None,
                  "checkpoints": str(checkpoint_dir) if checkpoint_dir else None},
        "files": files,
    }
    _write_manifest(bdir, manifest)
    _BACKUP_BYTES.inc(bytes_written)
    if deduped:
        _BACKUP_DEDUP.inc(deduped)
    return {"seq": seq, "dir": str(bdir), "mode": mode,
            "files": len(files), "bytes": bytes_written,
            "dedupedFiles": deduped,
            "basedOn": manifest["basedOn"]}


def verify_backup(bdir: Path, manifest: dict | None = None) -> list[str]:
    """Re-hash every file a backup's manifest lists; the list of
    violations (empty == restorable)."""
    bdir = Path(bdir)
    if manifest is None:
        manifest = read_manifest(bdir)
    if manifest is None:
        _VERIFY_FAILURES.inc()
        return [f"{bdir.name}: no valid manifest (torn or corrupt)"]
    bad: list[str] = []
    for f in manifest.get("files", ()):
        p = bdir / f["path"]
        try:
            if p.stat().st_size != f["bytes"]:
                bad.append(f"{f['path']}: size mismatch")
                continue
        except OSError:
            bad.append(f"{f['path']}: missing")
            continue
        if _sha256_file(p) != f["sha256"]:
            bad.append(f"{f['path']}: sha256 mismatch")
    if bad:
        _VERIFY_FAILURES.inc(len(bad))
    return bad


# --------------------------------------------------------------------------
# restore

def _home_nonempty(target: Path, backup_root: Path) -> bool:
    if not target.is_dir():
        return False
    for p in target.iterdir():
        if p.resolve() == backup_root.resolve():
            continue
        if p.name == "run" and p.is_dir():
            if any(q.name != "dr.lock" for q in p.iterdir()):
                return True
            continue
        return True
    return False


def _journal_roots(target: Path) -> list[Path]:
    """Top-level journal directories under ``target``: the parents of
    ``journal-*.log`` segments, collapsed through ``p<k>/`` partition
    subdirs to the partitioned root."""
    roots: set[Path] = set()
    for seg in target.rglob("journal-*.log"):
        if not _SEGMENT_RE.match(seg.name):
            continue
        d = seg.parent
        if re.fullmatch(r"p\d+", d.name) and (d.parent / "partitions.json").exists():
            d = d.parent
        roots.add(d)
    for pj in target.rglob("partitions.json"):
        roots.add(pj.parent)
    return sorted(roots)


def _iter_journal_dir(root: Path) -> Iterable[bytes]:
    """All records under one journal root, partition subdirs in order."""
    if (root / "partitions.json").exists():
        parts = sorted((d for d in root.iterdir()
                        if d.is_dir() and re.fullmatch(r"p\d+", d.name)),
                       key=lambda d: int(d.name[1:]))
        for d in parts:
            yield from iter_journal_records(d)
    else:
        yield from iter_journal_records(root)


def _parse_until(until) -> tuple[int | None, datetime | None]:
    """``--until`` is either a record ordinal (int: replay the first N
    WAL records) or an ISO-8601 timestamp (replay events with eventTime
    at or before it)."""
    if until is None:
        return None, None
    s = str(until).strip()
    if re.fullmatch(r"\d+", s):
        return int(s), None
    ts = datetime.fromisoformat(s.replace("Z", "+00:00"))
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=timezone.utc)
    return None, ts


def _replay_wal(target: Path, until) -> tuple[int, bool]:
    """Replay every event-journal record restored under ``target``
    through the id-keyed insert path (INSERT OR REPLACE by event_id —
    the drain loop's exactly-once discipline, so replay is idempotent).
    With a cut, the replayed journals are then removed: everything at
    or before the cut is in the database, everything after it must not
    survive for a later drainer to re-push."""
    from .event import event_from_api_dict
    from .sqlite import SQLiteEvents

    max_seq, max_ts = _parse_until(until)
    roots = [r for r in _journal_roots(target)
             if r.name != "delta-journal"]  # router deltas are not events
    if not roots:
        return 0, False
    backend = SQLiteEvents({"path": str(target / "events.db")})
    replayed = 0
    ordinal = 0
    try:
        groups: dict[tuple[int, int | None], list] = {}
        for root in roots:
            for payload in _iter_journal_dir(root):
                try:
                    obj = json.loads(payload)
                    ev = event_from_api_dict(obj["e"])
                    app_id = int(obj["a"])
                except (ValueError, KeyError, TypeError):
                    continue  # not an event record (foreign journal)
                ordinal += 1
                if max_seq is not None and ordinal > max_seq:
                    continue
                if max_ts is not None and ev.event_time is not None \
                        and ev.event_time > max_ts:
                    continue
                groups.setdefault((app_id, obj.get("c")), []).append(ev)
        for (app_id, channel_id), events in groups.items():
            for i in range(0, len(events), 500):
                backend.insert_batch(events[i:i + 500], app_id, channel_id)
            replayed += len(events)
    finally:
        close = getattr(backend, "close", None)
        if close:
            close()
    truncated = False
    if (max_seq is not None or max_ts is not None) and replayed >= 0:
        # point-in-time cut: drop the replayed WAL so a future drainer
        # cannot re-push post-cut records
        for root in roots:
            shutil.rmtree(root, ignore_errors=True)
            root.mkdir(parents=True, exist_ok=True)
        truncated = True
    _RESTORE_REPLAYED.inc(replayed)
    return replayed, truncated


def restore(backup_dir: str | os.PathLike,
            target_home: str | os.PathLike | None = None, *,
            backup_id: int | None = None, force: bool = False,
            until=None, replay: bool = True) -> dict:
    """Rebuild a home from a manifest-complete backup.

    Every checksum is re-verified before a single byte lands in the
    target; a non-empty target without ``force`` raises
    ``RestoreRefused`` (CLI exit 2).  Incomplete/corrupt backups are
    reported and never silently used.
    """
    from .registry import Storage
    root = Path(backup_dir)
    target = Path(target_home) if target_home is not None else Path(Storage.home())

    if not force and _home_nonempty(target, root):
        _RESTORE_TOTAL.labels(status="refused").inc()
        raise RestoreRefused(
            f"target {target} is not empty — pass --force to overwrite, "
            f"or restore into a fresh --target")

    complete, partial = list_backups(root)
    skipped = [s for s, _ in partial]
    if backup_id is not None:
        chosen = [c for c in complete if c[0] == backup_id]
        if not chosen:
            _RESTORE_TOTAL.labels(status="error").inc()
            if any(s == backup_id for s in skipped):
                raise BackupError(
                    f"backup {backup_id} is incomplete or corrupt "
                    f"(manifest missing/torn) — refusing to restore from it")
            raise BackupError(f"no backup {backup_id} under {root}")
        seq, bdir, manifest = chosen[0]
    elif complete:
        seq, bdir, manifest = complete[-1]
    else:
        _RESTORE_TOTAL.labels(status="error").inc()
        detail = f" ({len(skipped)} incomplete backup(s) ignored: " \
                 f"{skipped})" if skipped else ""
        raise BackupError(f"no complete backup under {root}{detail}")

    bad = verify_backup(bdir, manifest)
    if bad:
        _RESTORE_TOTAL.labels(status="verify_failed").inc()
        raise BackupError(
            f"backup {seq} failed verification, refusing to restore: "
            + "; ".join(bad[:5]))

    target.mkdir(parents=True, exist_ok=True)
    with _DrLock(target):
        applied = 0
        bytes_applied = 0
        try:
            for f in manifest["files"]:
                FAULTS.fire("restore.apply")
                rel = f["path"]
                prefix, _, tail = rel.partition("/")
                if prefix == "home":
                    dst = target / tail
                else:  # external journal/checkpoints roots land inside
                    dst = target / f"restored-{prefix}" / tail
                dst.parent.mkdir(parents=True, exist_ok=True)
                _copy_hashed(bdir / rel, dst)
                applied += 1
                bytes_applied += f["bytes"]
            replayed, truncated = (0, False)
            if replay:
                replayed, truncated = _replay_wal(target, until)
        except BaseException:
            _RESTORE_TOTAL.labels(status="error").inc()
            raise
        _RESTORE_TOTAL.labels(status="ok").inc()
        return {"backup": seq, "dir": str(bdir), "target": str(target),
                "files": applied, "bytes": bytes_applied,
                "replayedRecords": replayed, "walTruncated": truncated,
                "skippedPartial": skipped}


# --------------------------------------------------------------------------
# fsck

def _scan_segment_valid_len(path: Path) -> tuple[int, int]:
    """(valid byte length, whole-frame record count) of one segment."""
    valid = 0
    records = 0
    with open(path, "rb") as fh:
        while True:
            header = fh.read(_FRAME.size)
            if len(header) < _FRAME.size:
                break
            length, crc = _FRAME.unpack(header)
            payload = fh.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break
            valid += _FRAME.size + length
            records += 1
    return valid, records


def _quarantine(home: Path, path: Path) -> Path:
    """Move a corrupt artifact under ``$PIO_HOME/quarantine/`` keeping
    its relative shape — never deleted by repair, only set aside."""
    qroot = home / "quarantine"
    try:
        rel = path.resolve().relative_to(home.resolve())
    except ValueError:
        rel = Path(path.name)
    dst = qroot / rel
    dst.parent.mkdir(parents=True, exist_ok=True)
    if dst.exists():
        dst = dst.with_name(dst.name + f".{int(time.time())}")
    shutil.move(str(path), str(dst))
    return dst


def _find_orphan_blobs(home: Path) -> list[str]:
    """Model blob ids in ``$PIO_HOME/models`` referenced by no
    non-retired EngineInstance (ABORTED/ABANDONED count as retired)."""
    from .metadata import MetadataStore
    models = home / "models"
    meta_path = home / "metadata.db"
    if not models.is_dir() or not meta_path.is_file():
        return []
    store = MetadataStore(str(meta_path))
    try:
        live = {i.id for i in store.engine_instance_get_all()
                if i.status not in _RETIRED_STATUSES}
    finally:
        store.close()
    orphans = []
    for p in sorted(models.iterdir()):
        if not p.is_file() or p.name.endswith(".sha256"):
            continue
        if p.name not in live:
            orphans.append(p.name)
    return orphans


def fsck(home: str | os.PathLike | None = None, *,
         journal_dir: str | os.PathLike | None = None,
         checkpoint_dir: str | os.PathLike | None = None,
         repair: bool = False) -> dict:
    """Audit the cross-store integrity invariants; optionally repair.

    Invariants (one counter label each):
      blob          every COMPLETED instance's model blob exists and
                    matches its .sha256 sidecar
      checkpoint    every checkpoint step manifest lists only present,
                    checksum-matching shards
      journal       segments are validly framed to their tail; cursors
                    point at or before it
      router_epoch  the fleet router epoch marker is >= the max epoch
                    in its delta journal

    ``repair=True`` quarantines corrupt blobs/steps under
    ``$PIO_HOME/quarantine/``, truncates torn segments to their last
    valid frame, clamps cursors, and rewrites a regressed epoch marker.
    Nothing is deleted.
    """
    from .metadata import MetadataStore
    from .registry import Storage
    home = Path(home) if home is not None else Path(Storage.home())
    violations: list[dict] = []
    checked = {"blobs": 0, "checkpointSteps": 0, "journalSegments": 0,
               "routerEpoch": False}

    def flag(invariant: str, path: Path, detail: str,
             repaired: bool = False) -> None:
        violations.append({"invariant": invariant, "path": str(path),
                           "detail": detail, "repaired": repaired})
        _FSCK_VIOLATIONS.labels(invariant=invariant).inc()

    # -- blob invariant
    meta_path = home / "metadata.db"
    models = home / "models"
    if meta_path.is_file():
        store = MetadataStore(str(meta_path))
        try:
            completed = store.engine_instance_get_by_status("COMPLETED")
        finally:
            store.close()
        for inst in completed:
            blob = models / inst.id
            checked["blobs"] += 1
            if not blob.is_file():
                flag("blob", blob, f"COMPLETED instance {inst.id} has no blob")
                continue
            sidecar = models / f"{inst.id}.sha256"
            if not sidecar.is_file():
                continue  # pre-integrity blob: presence is the invariant
            want = sidecar.read_text().strip()
            got = "sha256:" + _sha256_file(blob)
            if want != got:
                repaired = False
                if repair:
                    _quarantine(home, blob)
                    _quarantine(home, sidecar)
                    repaired = True
                flag("blob", blob,
                     f"checksum mismatch (sidecar {want[:23]}..., "
                     f"blob {got[:23]}...)", repaired)

    # -- checkpoint invariant
    ckpt = Path(checkpoint_dir) if checkpoint_dir else home / "checkpoints"
    if ckpt.is_dir():
        for step_dir in sorted(ckpt.iterdir()):
            if not step_dir.is_dir() or not _STEP_RE.match(step_dir.name):
                continue
            checked["checkpointSteps"] += 1
            mf = step_dir / "manifest.json"
            try:
                manifest = json.loads(mf.read_text())
                shards = manifest["shards"]
            except (OSError, ValueError, KeyError):
                repaired = False
                if repair:
                    _quarantine(home, step_dir)
                    repaired = True
                flag("checkpoint", step_dir, "unparseable manifest (torn step)",
                     repaired)
                continue
            broken = None
            for sh in shards:
                p = step_dir / sh["file"]
                if not p.is_file():
                    broken = f"manifest lists missing shard {sh['file']}"
                    break
                if sh.get("sha256") and _sha256_file(p) != sh["sha256"]:
                    broken = f"shard {sh['file']} checksum mismatch"
                    break
            if broken:
                repaired = False
                if repair:
                    _quarantine(home, step_dir)
                    repaired = True
                flag("checkpoint", step_dir, broken, repaired)

    # -- journal invariant
    jroots = _journal_roots(home)
    if journal_dir is not None and Path(journal_dir).is_dir():
        jroots.extend(r for r in _journal_roots(Path(journal_dir))
                      if r not in jroots)
    seen_dirs: list[Path] = []
    for root in jroots:
        dirs = [root]
        if (root / "partitions.json").exists():
            dirs = sorted((d for d in root.iterdir()
                           if d.is_dir() and re.fullmatch(r"p\d+", d.name)),
                          key=lambda d: int(d.name[1:]))
        seen_dirs.extend(dirs)
    for d in seen_dirs:
        segs = sorted(d.glob("journal-*.log"))
        seg_valid: dict[int, int] = {}
        for seg in segs:
            m = _SEGMENT_RE.match(seg.name)
            if not m:
                continue
            checked["journalSegments"] += 1
            valid, _n = _scan_segment_valid_len(seg)
            seg_valid[int(m.group(1))] = valid
            size = seg.stat().st_size
            if valid < size:
                repaired = False
                if repair:
                    with open(seg, "r+b") as fh:
                        fh.truncate(valid)
                        fh.flush()
                        os.fsync(fh.fileno())
                    repaired = True
                flag("journal", seg,
                     f"torn frame: {size - valid} trailing bytes past last "
                     f"valid record (valid prefix {valid}B)", repaired)
        cursor_file = d / "cursor.json"
        if cursor_file.is_file() and seg_valid:
            try:
                cur = json.loads(cursor_file.read_text())
                cseq, coff = int(cur.get("seq", 0)), int(cur.get("off", 0))
            except (ValueError, TypeError):
                flag("journal", cursor_file, "unparseable cursor")
                continue
            max_seq = max(seg_valid)
            bad = None
            if cseq > max_seq:
                bad = f"cursor seq {cseq} past journal tail seq {max_seq}"
                cseq, coff = max_seq, seg_valid[max_seq]
            elif cseq in seg_valid and coff > seg_valid[cseq]:
                bad = (f"cursor offset {coff} past valid bytes "
                       f"{seg_valid[cseq]} of segment {cseq}")
                coff = seg_valid[cseq]
            if bad:
                repaired = False
                if repair:
                    cur["seq"], cur["off"] = cseq, coff
                    _atomic_json(cursor_file, cur)
                    repaired = True
                flag("journal", cursor_file, bad, repaired)

    # -- router epoch invariant
    rdir = home / "run" / "fleet-router"
    if rdir.is_dir():
        checked["routerEpoch"] = True
        floor = 0
        dj = rdir / "delta-journal"
        if dj.is_dir():
            for payload in iter_journal_records(dj):
                if len(payload) >= 8:
                    floor = max(floor,
                                int.from_bytes(payload[:8], "little"))
        marker = rdir / "epoch.json"
        epoch = 0
        doc: dict = {}
        if marker.is_file():
            try:
                doc = json.loads(marker.read_text())
                epoch = int(doc.get("epoch", 0))
            except (ValueError, TypeError):
                doc, epoch = {}, 0
        if floor > epoch:
            repaired = False
            if repair:
                doc["epoch"] = floor
                _atomic_json(marker, doc)
                repaired = True
            flag("router_epoch", marker,
                 f"marker epoch {epoch} behind delta-journal floor {floor}",
                 repaired)

    orphans = _find_orphan_blobs(home)
    _FSCK_ORPHAN_BLOBS.set(len(orphans))

    verdict = "clean" if not violations else f"{len(violations)} violation(s)"
    _FSCK_RUNS.labels(verdict="clean" if not violations else "violations").inc()
    repaired_n = sum(1 for v in violations if v["repaired"])
    report = {"verdict": verdict, "violations": violations,
              "repaired": repaired_n, "orphanBlobs": orphans,
              "checked": checked}
    try:
        (home / "run").mkdir(parents=True, exist_ok=True)
        _atomic_json(home / "run" / FSCK_STATE,
                     {"at": _utcnow_iso(), "verdict": verdict,
                      "violations": len(violations), "repaired": repaired_n,
                      "orphanBlobs": len(orphans)})
    except OSError:
        pass  # status surface only; the audit itself already ran
    return report


def gc_blobs(home: str | os.PathLike | None = None, *,
             dry_run: bool = False) -> dict:
    """Delete model blobs (and their sidecars) referenced by no
    non-retired EngineInstance.  ``dry_run`` only reports."""
    from .registry import Storage
    home = Path(home) if home is not None else Path(Storage.home())
    orphans = _find_orphan_blobs(home)
    deleted = 0
    if not dry_run:
        models = home / "models"
        for name in orphans:
            for p in (models / name, models / f"{name}.sha256"):
                try:
                    p.unlink()
                except FileNotFoundError:
                    pass
            deleted += 1
        if deleted:
            _FSCK_GC_DELETED.inc(deleted)
        _FSCK_ORPHAN_BLOBS.set(0)
    else:
        _FSCK_ORPHAN_BLOBS.set(len(orphans))
    return {"orphans": orphans, "deleted": deleted, "dryRun": dry_run}


# --------------------------------------------------------------------------
# status surface + bench

def status_lines(home: str | os.PathLike | None = None,
                 backup_dir: str | os.PathLike | None = None) -> list[str]:
    """Human lines for `pio status`: last-backup age, last-fsck verdict,
    orphan-blob count."""
    from .registry import Storage
    home = Path(home) if home is not None else Path(Storage.home())
    root = Path(backup_dir) if backup_dir is not None else home / "backups"
    lines: list[str] = []
    complete, partial = list_backups(root)
    if complete:
        seq, _p, manifest = complete[-1]
        age = ""
        try:
            created = datetime.fromisoformat(manifest["createdAt"])
            secs = max(0, int((datetime.now(timezone.utc) - created)
                              .total_seconds()))
            age = f", age {secs}s"
        except (KeyError, ValueError):
            pass
        extra = f" ({len(partial)} incomplete ignored)" if partial else ""
        lines.append(f"last backup: #{seq}{age}, "
                     f"{len(complete)} complete{extra}")
    else:
        lines.append("last backup: none (run `pio backup`)")
    state = home / "run" / FSCK_STATE
    if state.is_file():
        try:
            doc = json.loads(state.read_text())
            lines.append(f"last fsck: {doc.get('verdict', '?')} "
                         f"at {doc.get('at', '?')}, "
                         f"{doc.get('orphanBlobs', 0)} orphan blob(s)")
        except (ValueError, OSError):
            lines.append("last fsck: state unreadable")
    else:
        lines.append("last fsck: never (run `pio admin fsck`)")
    return lines


def run_backup_bench(*, files: int = 64, size_kb: int = 256,
                     rounds: int = 2) -> dict:
    """Synthetic backup throughput: a temp home of ``files`` blobs of
    ``size_kb`` each, one full backup then ``rounds-1`` incrementals
    (all-unchanged → pure dedup).  Prints MB/s and dedup counts."""
    import tempfile

    results = []
    with tempfile.TemporaryDirectory(prefix="pio-bench-backup-") as td:
        home = Path(td) / "home"
        (home / "models").mkdir(parents=True)
        blob = os.urandom(size_kb * 1024)
        for i in range(files):
            (home / "models" / f"bench-{i:04d}").write_bytes(blob[:-(i % 7 + 1)])
        root = Path(td) / "backups"
        for r in range(max(1, rounds)):
            t0 = time.monotonic()
            rep = create_backup(home, backup_dir=root, keep=rounds + 1)
            dt = time.monotonic() - t0
            mb = rep["bytes"] / 1e6
            results.append({"round": r, "seconds": round(dt, 4),
                            "mbWritten": round(mb, 3),
                            "mbPerS": round(mb / dt, 2) if dt > 0 else 0.0,
                            "dedupedFiles": rep["dedupedFiles"]})
    return {"files": files, "sizeKb": size_kb, "rounds": results}
