"""The universal event schema and its validation rules.

Analog of the reference's ``Event`` case class and ``EventValidation``
(reference: data/src/main/scala/io/prediction/data/storage/Event.scala:37-115).

Every interaction recorded by the framework — a rating, a page view, a
``$set`` of entity properties — is one ``Event``. Events are immutable;
the event store assigns ``event_id`` at insert time.
"""

from __future__ import annotations

import copy
import json
import uuid
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Mapping, Sequence

from .datamap import DataMap

__all__ = [
    "Event",
    "ValidationError",
    "validate_event",
    "event_to_api_dict",
    "event_from_api_dict",
    "SPECIAL_EVENTS",
]

#: Single-entity reserved events (Event.scala:68).
SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})

#: Built-in entity types allowed despite the reserved prefix (Event.scala:106).
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})


class ValidationError(ValueError):
    """An event failed schema validation."""


def _utcnow() -> datetime:
    return datetime.now(timezone.utc)


@dataclass(frozen=True)
class Event:
    """One immutable event record. Field names follow the REST API's JSON
    (camelCase on the wire; snake_case here)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: str | None = None
    target_entity_id: str | None = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: datetime = field(default_factory=_utcnow)
    tags: Sequence[str] = ()
    pr_id: str | None = None
    event_id: str | None = None
    creation_time: datetime = field(default_factory=_utcnow)

    def with_id(self, event_id: str | None = None) -> "Event":
        # copy + setattr instead of dataclasses.replace: replace() rebuilds
        # the full kwargs dict and re-runs __post_init__ validation the
        # source event already passed — ~7x slower on the bulk-import path
        # where every parsed event gets an id stamped
        new = copy.copy(self)
        object.__setattr__(new, "event_id", event_id or uuid.uuid4().hex)
        return new

    def __post_init__(self):
        if self.event_time.tzinfo is None:
            object.__setattr__(
                self, "event_time", self.event_time.replace(tzinfo=timezone.utc)
            )
        if self.creation_time.tzinfo is None:
            object.__setattr__(
                self, "creation_time", self.creation_time.replace(tzinfo=timezone.utc)
            )
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap.from_dict(self.properties))


def is_reserved_prefix(name: str) -> bool:
    return name.startswith("$") or name.startswith("pio_")


def validate_event(e: Event) -> None:
    """Enforce the reference's validation rules (Event.scala:70-115):
    non-empty names, paired target entity, reserved ``$``/``pio_`` prefixes,
    non-empty properties for ``$unset``, no target on special events.
    """
    def check(cond: bool, msg: str) -> None:
        if not cond:
            raise ValidationError(msg)

    check(bool(e.event), "event must not be empty.")
    check(bool(e.entity_type), "entityType must not be empty string.")
    check(bool(e.entity_id), "entityId must not be empty string.")
    check(e.target_entity_type != "", "targetEntityType must not be empty string")
    check(e.target_entity_id != "", "targetEntityId must not be empty string.")
    check(
        (e.target_entity_type is None) == (e.target_entity_id is None),
        "targetEntityType and targetEntityId must be specified together.",
    )
    check(
        not (e.event == "$unset" and e.properties.is_empty),
        "properties cannot be empty for $unset event",
    )
    check(
        not is_reserved_prefix(e.event) or e.event in SPECIAL_EVENTS,
        f"{e.event} is not a supported reserved event name.",
    )
    check(
        e.event not in SPECIAL_EVENTS
        or (e.target_entity_type is None and e.target_entity_id is None),
        f"Reserved event {e.event} cannot have targetEntity",
    )
    check(
        not is_reserved_prefix(e.entity_type) or e.entity_type in BUILTIN_ENTITY_TYPES,
        f"The entityType {e.entity_type} is not allowed. "
        "'pio_' is a reserved name prefix.",
    )
    if e.target_entity_type is not None:
        check(
            not is_reserved_prefix(e.target_entity_type)
            or e.target_entity_type in BUILTIN_ENTITY_TYPES,
            f"The targetEntityType {e.target_entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.",
        )
    for k in e.properties.key_set():
        check(
            not is_reserved_prefix(k),
            f"The property {k} is not allowed. 'pio_' is a reserved name prefix.",
        )


# ---------------------------------------------------------------------------
# Wire format — the REST API JSON shape (reference: EventJson4sSupport.scala
# APISerializer, data/.../storage/EventJson4sSupport.scala:40-130).
# ---------------------------------------------------------------------------

def _dt_to_wire(t: datetime) -> str:
    return t.astimezone(timezone.utc).isoformat().replace("+00:00", "Z")


def _dt_from_wire(s: str) -> datetime:
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    t = datetime.fromisoformat(s)
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t


def event_to_api_dict(e: Event) -> dict[str, Any]:
    d: dict[str, Any] = {
        "event": e.event,
        "entityType": e.entity_type,
        "entityId": e.entity_id,
        "properties": e.properties.to_dict(),
        "eventTime": _dt_to_wire(e.event_time),
        "creationTime": _dt_to_wire(e.creation_time),
    }
    if e.event_id is not None:
        d["eventId"] = e.event_id
    if e.target_entity_type is not None:
        d["targetEntityType"] = e.target_entity_type
        d["targetEntityId"] = e.target_entity_id
    if e.tags:
        d["tags"] = list(e.tags)
    if e.pr_id is not None:
        d["prId"] = e.pr_id
    return d


def event_from_api_dict(d: Mapping[str, Any]) -> Event:
    try:
        event = d["event"]
        entity_type = d["entityType"]
        entity_id = d["entityId"]
    except KeyError as err:
        raise ValidationError(f"field {err.args[0]} is required") from err
    for name in ("event", "entityType", "entityId"):
        if not isinstance(d[name], str):
            raise ValidationError(f"field {name} must be a string")
    for name in ("targetEntityType", "targetEntityId", "prId", "eventId"):
        if d.get(name) is not None and not isinstance(d[name], str):
            raise ValidationError(f"field {name} must be a string")
    tags = d.get("tags", ())
    if isinstance(tags, str) or not isinstance(tags, (list, tuple)):
        raise ValidationError("field tags must be a JSON array of strings")
    if any(not isinstance(t, str) for t in tags):
        raise ValidationError("field tags must be a JSON array of strings")
    props = d.get("properties", {})
    if not isinstance(props, Mapping):
        raise ValidationError("field properties must be a JSON object")
    kwargs: dict[str, Any] = {}
    for wire, attr in (("eventTime", "event_time"), ("creationTime", "creation_time")):
        if wire in d:
            try:
                kwargs[attr] = _dt_from_wire(d[wire])
            except (ValueError, TypeError) as err:
                raise ValidationError(f"field {wire} must be ISO8601: {err}") from err
    e = Event(
        event=event,
        entity_type=entity_type,
        entity_id=entity_id,
        target_entity_type=d.get("targetEntityType"),
        target_entity_id=d.get("targetEntityId"),
        properties=DataMap.from_dict(props),
        tags=tuple(d.get("tags", ())),
        pr_id=d.get("prId"),
        event_id=d.get("eventId"),
        **kwargs,
    )
    validate_event(e)
    return e


def event_to_json(e: Event) -> str:
    return json.dumps(event_to_api_dict(e), sort_keys=True)


def event_from_json(s: str) -> Event:
    return event_from_api_dict(json.loads(s))
