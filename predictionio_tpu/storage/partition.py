"""Entity hash-sharding — the HBase row-key prefix, TPU-native.

The reference spreads event rows across HBase regions with an 8-byte MD5
prefix of ``entityType + "-" + entityId`` (reference: data/src/main/scala/
io/prediction/data/storage/hbase/HBEventsUtil.scala:74-134 ``RowKey``).
Here the same role — deterministic, uniform placement of an entity's
events onto a shard — is played by a 64-bit FNV-1a/splitmix64 hash,
computed by the native C++ kernel (``pio_hash64_batch``) when built, with
a bit-identical pure-Python fallback. Multi-host data loading partitions
event streams by ``shard_of(...) == host_index`` so every host ingests a
disjoint slice before ``device_put`` onto its local mesh slice.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from .. import native
from .event import Event

__all__ = ["entity_key", "hash64", "iter_host_shard", "partition_events", "shard_of"]

_M = 0xFFFFFFFFFFFFFFFF


def _fnv1a64(data: bytes, seed: int) -> int:
    h = 0xCBF29CE484222325 ^ seed
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & _M
    return int(native.splitmix64_np(np.array([h], dtype=np.uint64))[0])


def entity_key(entity_type: str, entity_id: str) -> bytes:
    """Same composition as the reference row key: type ‖ '-' ‖ id."""
    return f"{entity_type}-{entity_id}".encode()


def hash64(keys: Sequence[bytes] | Sequence[str], seed: int = 0) -> np.ndarray:
    """Batch 64-bit hashes; native kernel when available, else pure Python
    (identical output)."""
    out = native.hash64_batch(list(keys), seed)
    if out is not None:
        return out
    bs = [k.encode() if isinstance(k, str) else k for k in keys]
    return np.array([_fnv1a64(b, seed) for b in bs], dtype=np.uint64)


def shard_of(entity_type: str, entity_id: str, num_shards: int, seed: int = 0) -> int:
    return int(hash64([entity_key(entity_type, entity_id)], seed)[0] % num_shards)


def iter_host_shard(
    events: Iterable[Event], index: int, count: int, seed: int = 0,
    _chunk: int = 8192,
) -> Iterable[Event]:
    """Stream only the events whose entity hashes to shard ``index`` of
    ``count`` — chunked so the native batch hash does the work while peak
    memory stays one chunk, not the full stream."""
    if count < 1 or not (0 <= index < count):
        raise ValueError(f"invalid shard ({index}, {count})")
    buf: list[Event] = []

    def flush():
        hs = hash64([entity_key(e.entity_type, e.entity_id) for e in buf], seed)
        for e, h in zip(buf, hs):
            if int(h % np.uint64(count)) == index:
                yield e

    for e in events:
        buf.append(e)
        if len(buf) >= _chunk:
            yield from flush()
            buf = []
    if buf:
        yield from flush()


def partition_events(
    events: Iterable[Event], num_shards: int, seed: int = 0
) -> list[list[Event]]:
    """Split an event stream into ``num_shards`` disjoint lists by entity
    hash, keeping each entity's full history on one shard (the property a
    $set/$unset/$delete fold needs to run shard-locally — see
    storage/aggregate.py)."""
    evs = list(events)
    if not evs:
        return [[] for _ in range(num_shards)]
    hs = hash64([entity_key(e.entity_type, e.entity_id) for e in evs], seed)
    shards: list[list[Event]] = [[] for _ in range(num_shards)]
    for e, h in zip(evs, hs):
        shards[int(h % num_shards)].append(e)
    return shards
