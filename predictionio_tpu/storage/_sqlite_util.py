"""Shared sqlite helpers for the in-memory mode of the storage backends.

A ``:memory:`` database is private to one connection, so memory mode must
share a single connection between threads. Python's sqlite3 serializes
individual C calls, but lazy cursor iteration interleaved across threads on
one connection is not safe. ``LockedConnection`` makes every statement
atomic: it takes the store's lock, executes, materializes all rows, and
returns a detached result — so callers can keep the exact same
``conn.execute(...)`` / iterate / ``fetchone`` code paths they use with
per-thread file connections.
"""

from __future__ import annotations

import sqlite3
import threading

__all__ = ["LockedConnection"]


class _Rows:
    """A fully-materialized, detached cursor result."""

    __slots__ = ("_rows", "rowcount", "lastrowid")

    def __init__(self, rows: list, rowcount: int, lastrowid: int | None):
        self._rows = rows
        self.rowcount = rowcount
        self.lastrowid = lastrowid

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self) -> list:
        return list(self._rows)

    def __iter__(self):
        return iter(self._rows)


class LockedConnection:
    """Single shared sqlite connection; each call locks + materializes."""

    def __init__(self, path: str, lock: threading.RLock):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = lock

    def execute(self, sql: str, params: tuple | list = ()) -> _Rows:
        with self._lock:
            cur = self._conn.execute(sql, params)
            rows = cur.fetchall() if cur.description is not None else []
            return _Rows(rows, cur.rowcount, cur.lastrowid)

    def executemany(self, sql: str, seq) -> _Rows:
        with self._lock:
            cur = self._conn.executemany(sql, seq)
            return _Rows([], cur.rowcount, cur.lastrowid)

    def executescript(self, script: str) -> None:
        with self._lock:
            self._conn.executescript(script)

    def commit(self) -> None:
        with self._lock:
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
