"""Typed JSON property bags.

``DataMap`` is the universal property container attached to every event and
aggregated entity — the analog of the reference's immutable json4s-backed
``DataMap`` (reference: data/src/main/scala/io/prediction/data/storage/
DataMap.scala:38-193) and ``PropertyMap`` (PropertyMap.scala:33).

Values are plain JSON-compatible Python values (str, int, float, bool, None,
list, dict). The map is immutable: mutating operations return new maps.
"""

from __future__ import annotations

import json
from datetime import datetime
from typing import Any, Iterable, Iterator, Mapping

__all__ = ["DataMap", "PropertyMap", "DataMapError"]


class DataMapError(KeyError):
    """Raised when a required field is missing or has the wrong type."""


_JSON_TYPES = (str, int, float, bool, list, dict, type(None))


def _check_json(value: Any) -> Any:
    if isinstance(value, datetime):
        return value.isoformat()
    if not isinstance(value, _JSON_TYPES):
        raise TypeError(f"DataMap values must be JSON-compatible, got {type(value)!r}")
    return value


class DataMap:
    """An immutable map of field name -> JSON value.

    Mirrors the accessor surface of the reference DataMap: ``get`` (required,
    raises on absence), ``get_opt`` (optional), ``get_or_else``, set-algebra
    ``union``/``difference`` (the reference's ``++``/``--``,
    DataMap.scala:134-145), and typed extraction.

    Deliberately NOT a ``collections.abc.Mapping``: ``get(name, cls)`` here
    is the reference's typed required accessor (raises on absence), which
    contradicts ``Mapping.get(key, default)`` — registering as a Mapping
    would hand that trap to any generic code. Iteration/len/`in`/`==dict`
    still work structurally.
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Mapping[str, Any] | None = None):
        self._fields: dict[str, Any] = dict(fields) if fields else {}

    # -- structural mapping protocol --------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:  # stable enough for memo keys
        return hash(json.dumps(self._fields, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    def keys(self):
        return self._fields.keys()

    def values(self):
        return self._fields.values()

    def items(self):
        return self._fields.items()

    # -- accessors --------------------------------------------------------
    @property
    def fields(self) -> dict[str, Any]:
        return dict(self._fields)

    def require(self, name: str) -> None:
        if name not in self._fields:
            raise DataMapError(f"The field {name} is required.")

    def contains(self, name: str) -> bool:
        return name in self._fields

    def get(self, name: str, cls: type | None = None) -> Any:
        """Required typed accessor. Raises ``DataMapError`` if absent or null.

        If ``cls`` is given, the value is coerced/validated to that type
        (int/float interconversion allowed, as JSON does not distinguish).
        """
        if cls is not None and not isinstance(cls, type):
            raise TypeError(
                "DataMap.get(name, cls) takes a type, not a default value; "
                "use get_or_else(name, default)"
            )
        self.require(name)
        value = self._fields[name]
        if value is None:
            raise DataMapError(f"The required field {name} cannot be null.")
        return _coerce(name, value, cls)

    def get_opt(self, name: str, cls: type | None = None) -> Any | None:
        if name not in self._fields or self._fields[name] is None:
            return None
        return _coerce(name, self._fields[name], cls)

    def get_or_else(self, name: str, default: Any) -> Any:
        value = self.get_opt(name)
        return default if value is None else value

    def get_string_list(self, name: str) -> list[str]:
        value = self.get(name, list)
        return [str(v) for v in value]

    def get_double(self, name: str) -> float:
        return float(self.get(name))

    # -- algebra (reference DataMap.scala:134-151) ------------------------
    def union(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def __add__(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        return self.union(other)

    def difference(self, keys: Iterable[str]) -> "DataMap":
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def __sub__(self, keys: Iterable[str]) -> "DataMap":
        return self.difference(keys)

    @property
    def is_empty(self) -> bool:
        return not self._fields

    def key_set(self) -> set[str]:
        return set(self._fields)

    # -- (de)serialization ------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return dict(self._fields)

    def to_json(self) -> str:
        return json.dumps(self._fields, sort_keys=True)

    @staticmethod
    def from_dict(d: Mapping[str, Any] | None) -> "DataMap":
        if d is None:
            return DataMap()
        return DataMap({k: _check_json(v) for k, v in d.items()})

    @staticmethod
    def from_json(s: str) -> "DataMap":
        parsed = json.loads(s)
        if not isinstance(parsed, dict):
            raise DataMapError(f"DataMap JSON must be an object, got {type(parsed)}")
        return DataMap(parsed)


def _coerce(name: str, value: Any, cls: type | None) -> Any:
    if cls is None:
        return value
    if cls is float and isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    if cls is int and isinstance(value, (int, float)) and not isinstance(value, bool):
        if isinstance(value, float) and not value.is_integer():
            raise DataMapError(f"Field {name}={value!r} is not an integer.")
        return int(value)
    if cls is datetime and isinstance(value, str):
        return datetime.fromisoformat(value)
    if not isinstance(value, cls):
        raise DataMapError(
            f"Field {name} has type {type(value).__name__}, expected {cls.__name__}."
        )
    return value


class PropertyMap(DataMap):
    """DataMap plus first/last update times — the output of ``$set``/``$unset``
    aggregation (reference: PropertyMap.scala:33-99).
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Mapping[str, Any] | None,
        first_updated: datetime,
        last_updated: datetime,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self._fields!r}, firstUpdated={self.first_updated}, "
            f"lastUpdated={self.last_updated})"
        )

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self._fields == other._fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    __hash__ = DataMap.__hash__
