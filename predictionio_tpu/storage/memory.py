"""In-process event store backend.

Plays the role the reference's HBase backend plays in production and its
test doubles play in specs (reference: data/src/main/scala/io/prediction/
data/storage/hbase/HBLEvents.scala) — but as a thread-safe in-memory store,
the default for tests and single-process quickstarts.

Events are kept sorted by (event_time, insertion seq) per (app, channel) so
scans are ordered without per-query sorts; insertion is O(log n) bisect.
"""

from __future__ import annotations

import bisect
import threading
import uuid
from typing import Iterator

from .event import Event
from .events_base import EventBackend, EventQuery, TableNotInitialized

__all__ = ["MemoryEvents"]


class _Table:
    __slots__ = ("keys", "events", "by_id", "seq")

    def __init__(self):
        self.keys: list[tuple[float, int]] = []  # (epoch, seq) sort keys
        self.events: list[Event] = []
        self.by_id: dict[str, Event] = {}
        self.seq = 0


class MemoryEvents(EventBackend):
    BATCH_ATOMIC = True  # see insert_batch: validated upfront, one lock

    def __init__(self, config: dict | None = None):
        self._tables: dict[tuple[int, int | None], _Table] = {}
        self._lock = threading.RLock()

    def _table(self, app_id: int, channel_id: int | None, create: bool = False) -> _Table:
        key = (app_id, channel_id)
        with self._lock:
            t = self._tables.get(key)
            if t is None:
                if not create:
                    raise TableNotInitialized(
                        f"events table for app {app_id} channel {channel_id} "
                        "not initialized (run init_app / `pio app new`)"
                    )
                t = self._tables[key] = _Table()
            return t

    # -- lifecycle --------------------------------------------------------
    def init_app(self, app_id: int, channel_id: int | None = None) -> bool:
        self._table(app_id, channel_id, create=True)
        return True

    def remove_app(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._lock:
            return self._tables.pop((app_id, channel_id), None) is not None

    # -- writes -----------------------------------------------------------
    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        t = self._table(app_id, channel_id, create=True)
        with self._lock:
            e = event if event.event_id else event.with_id(uuid.uuid4().hex)
            if e.event_id in t.by_id:
                self._remove_from_lists(t, e.event_id)
            key = (e.event_time.timestamp(), t.seq)
            t.seq += 1
            pos = bisect.bisect_right(t.keys, key)
            t.keys.insert(pos, key)
            t.events.insert(pos, e)
            t.by_id[e.event_id] = e  # type: ignore[index]
            return e.event_id  # type: ignore[return-value]

    def insert_batch(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """All-or-nothing by construction (BATCH_ATOMIC): ids are
        assigned before any mutation and the appends are plain in-process
        list/dict operations under one lock — there is no failure path
        between the first and last event."""
        t = self._table(app_id, channel_id, create=True)
        out = []
        with self._lock:
            for event in events:
                e = event if event.event_id else event.with_id(uuid.uuid4().hex)
                if e.event_id in t.by_id:
                    self._remove_from_lists(t, e.event_id)
                key = (e.event_time.timestamp(), t.seq)
                t.seq += 1
                pos = bisect.bisect_right(t.keys, key)
                t.keys.insert(pos, key)
                t.events.insert(pos, e)
                t.by_id[e.event_id] = e
                out.append(e.event_id)
        return out

    @staticmethod
    def _remove_from_lists(t: _Table, event_id: str) -> None:
        for i, ev in enumerate(t.events):
            if ev.event_id == event_id:
                del t.events[i]
                del t.keys[i]
                break

    # -- point ops --------------------------------------------------------
    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        t = self._table(app_id, channel_id)
        with self._lock:
            return t.by_id.get(event_id)

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        t = self._table(app_id, channel_id)
        with self._lock:
            e = t.by_id.pop(event_id, None)
            if e is None:
                return False
            self._remove_from_lists(t, event_id)
            return True

    # -- scans ------------------------------------------------------------
    def find(self, query: EventQuery) -> Iterator[Event]:
        t = self._table(query.app_id, query.channel_id)
        with self._lock:
            events = list(t.events)  # snapshot; already time-ordered
        if query.reversed:
            events = events[::-1]
        limit = query.limit if query.limit is not None and query.limit >= 0 else None
        n = 0
        for e in events:
            if query.matches(e):
                yield e
                n += 1
                if limit is not None and n >= limit:
                    return
