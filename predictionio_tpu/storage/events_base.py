"""Event-store SPI.

The query surface mirrors the reference's ``LEvents``/``PEvents`` traits
(reference: data/src/main/scala/io/prediction/data/storage/LEvents.scala:31-451,
PEvents.scala:30-138): time-range, entity, event-name and target-entity
filters, limit and reversal, plus ``$set``-fold property aggregation.

Differences from the reference, by design:

- One backend class serves both the "local" (iterator) and "parallel" roles.
  The parallel read path is ``find_frame`` which returns a columnar
  ``EventFrame`` (see frame.py) instead of an ``RDD[Event]`` — the frame is
  what gets sharded onto the device mesh.
- Synchronous core methods; the event server wraps them in worker threads.
  (The reference's Futures exist because HBase RPCs are slow; the built-in
  backends here are in-process.)

Target-entity filters use the ``ANY`` sentinel: ``ANY`` = no restriction,
``None`` = event must have no target entity, a string = exact match —
the reference's ``None`` / ``Some(None)`` / ``Some(Some(x))`` triple
(LEvents.scala:111-118).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from datetime import datetime
from typing import Any, Iterator, Sequence

from .aggregate import aggregate_properties_frame, aggregate_properties_single
from .datamap import PropertyMap
from .event import Event
from .frame import EventFrame

__all__ = ["ANY", "EventBackend", "EventQuery", "StorageError",
           "TableNotInitialized"]


class StorageError(RuntimeError):
    pass


class TableNotInitialized(StorageError):
    """The per-app events table was never ``init_app``'d — the one
    storage failure that legitimately reads as 404 on the API's read and
    delete paths. Every other ``StorageError`` is a real backend fault
    and must surface as 500, not masquerade as "Not Found"."""


class _Any:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "ANY"


#: no-restriction sentinel for target-entity filters
ANY: Any = _Any()


@dataclass(frozen=True)
class EventQuery:
    """All find() filters in one value (hashable, usable as a memo key)."""

    app_id: int
    channel_id: int | None = None
    start_time: datetime | None = None
    until_time: datetime | None = None
    entity_type: str | None = None
    entity_id: str | None = None
    event_names: tuple[str, ...] | None = None
    target_entity_type: Any = ANY
    target_entity_id: Any = ANY
    limit: int | None = None
    reversed: bool = False

    def __post_init__(self):
        # naive datetimes are treated as UTC, matching Event.__post_init__ —
        # otherwise backends would compare/encode them in server-local time
        from datetime import timezone

        for name in ("start_time", "until_time"):
            t = getattr(self, name)
            if t is not None and t.tzinfo is None:
                object.__setattr__(self, name, t.replace(tzinfo=timezone.utc))

    def matches(self, e: Event) -> bool:
        if self.start_time is not None and e.event_time < self.start_time:
            return False
        if self.until_time is not None and e.event_time >= self.until_time:
            return False
        if self.entity_type is not None and e.entity_type != self.entity_type:
            return False
        if self.entity_id is not None and e.entity_id != self.entity_id:
            return False
        if self.event_names is not None and e.event not in self.event_names:
            return False
        if self.target_entity_type is not ANY:
            if e.target_entity_type != self.target_entity_type:
                return False
        if self.target_entity_id is not ANY:
            if e.target_entity_id != self.target_entity_id:
                return False
        return True


class EventBackend(abc.ABC):
    """Abstract event store. One instance manages all apps/channels of one
    configured EVENTDATA source (reference: Storage.getLEvents,
    Storage.scala:283-296)."""

    # -- lifecycle (LEvents.scala:44-68) ----------------------------------
    @abc.abstractmethod
    def init_app(self, app_id: int, channel_id: int | None = None) -> bool:
        """Initialize storage for an app/channel (idempotent)."""

    @abc.abstractmethod
    def remove_app(self, app_id: int, channel_id: int | None = None) -> bool:
        """Remove all events of an app/channel."""

    def close(self) -> None:
        pass

    # -- writes -----------------------------------------------------------
    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        """Insert one event, returning its assigned event id."""

    #: True when ``insert_batch`` is all-or-nothing (a failure persists
    #: NOTHING). The event server only takes the batch fast path for
    #: atomic backends — a partial insert followed by a blanket 500 would
    #: make clients re-send events that already landed.
    BATCH_ATOMIC = False

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """Bulk insert (the import path; reference tools/imprt/FileToEvents
        uses PEvents.write). Backends may override for a faster path; an
        all-or-nothing override should also set ``BATCH_ATOMIC``."""
        return [self.insert(e, app_id, channel_id) for e in events]

    # -- point reads / deletes (LEvents.scala:71-103) ---------------------
    @abc.abstractmethod
    def get(self, event_id: str, app_id: int, channel_id: int | None = None) -> Event | None:
        ...

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        ...

    def remove_before(self, app_id: int, cutoff, channel_id: int | None = None) -> int:
        """Delete every event with ``event_time < cutoff``; returns the
        count removed. The data-ageing verb behind
        ``pio app data-delete --before`` (role of the reference's
        trim-app engine, examples/experimental/scala-parallel-trim-app —
        which re-reads and re-writes the keep-window instead). Generic
        fallback: scan + per-event delete; backends override with a bulk
        path."""
        ids = [e.event_id for e in
               self.find(EventQuery(app_id=app_id, channel_id=channel_id,
                                    until_time=cutoff))]
        removed = 0
        for eid in ids:
            removed += bool(self.delete(eid, app_id, channel_id))
        return removed

    # -- queries ----------------------------------------------------------
    @abc.abstractmethod
    def find(self, query: EventQuery) -> Iterator[Event]:
        """Filtered scan ordered by event_time (descending if
        ``query.reversed``), truncated to ``query.limit`` (None or -1 = all)."""

    def find_frame(self, query: EventQuery) -> EventFrame:
        """Columnar scan — the parallel/TPU read path (PEvents.find analog).
        Limit/reversed are ignored (full filtered scan), as in the
        reference's parallel API which has no limit (PEvents.scala:70-80)."""
        q = EventQuery(**{**query.__dict__, "limit": None, "reversed": False})
        return EventFrame.from_events(self.find(q))

    def aggregate_properties(
        self,
        app_id: int,
        *,
        entity_type: str,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        required: Sequence[str] | None = None,
    ) -> dict[str, PropertyMap]:
        """$set/$unset/$delete fold per entity (LEvents.scala:153-194).

        Reads through ``find_frame`` (one columnar scan) and the
        vectorized frame fold — the ISSUE 9 read pushdown; semantics are
        pinned bit-identical to the row-at-a-time
        ``aggregate_properties(self.find(...))`` it replaces."""
        frame = self.find_frame(
            EventQuery(
                app_id=app_id,
                channel_id=channel_id,
                entity_type=entity_type,
                start_time=start_time,
                until_time=until_time,
                event_names=("$set", "$unset", "$delete"),
            )
        )
        result = aggregate_properties_frame(frame)
        if required:
            result = {
                k: v
                for k, v in result.items()
                if all(r in v for r in required)
            }
        return result

    def aggregate_properties_of_entity(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
    ) -> PropertyMap | None:
        """Single-entity fold (LEvents.scala:196-230)."""
        events = self.find(
            EventQuery(
                app_id=app_id,
                channel_id=channel_id,
                entity_type=entity_type,
                entity_id=entity_id,
                start_time=start_time,
                until_time=until_time,
                event_names=("$set", "$unset", "$delete"),
            )
        )
        return aggregate_properties_single(events)
