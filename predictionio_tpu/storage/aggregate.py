"""Property aggregation: folding ``$set``/``$unset``/``$delete`` event
streams into per-entity ``PropertyMap``s.

Reference semantics (data/src/main/scala/io/prediction/data/storage/
LEventAggregator.scala:22-123 and PEventAggregator.scala:35-209):

- events are processed in ``event_time`` order per entity;
- ``$set`` merges properties (later wins per key);
- ``$unset`` removes the named keys (only if the entity currently exists);
- ``$delete`` erases the entity (it may be re-created by a later ``$set``);
- entities whose fold ends with no live DataMap are dropped;
- first/last updated times span all special events seen for the entity.

The parallel version in the reference is an ``aggregateByKey`` over a
commutative monoid (``EventOp ++``). Here the same monoid is implemented so
aggregation can run as an associative merge over event shards — the
host-side analog of a segment reduce — and is therefore safe to parallelize
over processes or to fold incrementally as events stream in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Iterable, Iterator

import numpy as np

from .datamap import PropertyMap
from .event import Event

__all__ = ["EventOp", "aggregate_properties", "aggregate_properties_frame",
           "aggregate_properties_single"]


def _millis(t: datetime) -> float:
    return t.timestamp()


@dataclass
class _PropTime:
    value: Any
    t: float


@dataclass
class EventOp:
    """The aggregation monoid (reference PEventAggregator.scala:95-190).

    Tracks, independently: last-write-wins ``$set`` fields, latest ``$unset``
    time per key, latest ``$delete`` time, and first/last updated times.
    ``merge`` is associative and commutative, so shard-level partial
    aggregates combine in any order.
    """

    set_fields: dict[str, _PropTime] = field(default_factory=dict)
    set_t: float | None = None  # latest $set time (fields may be empty)
    unset_fields: dict[str, float] = field(default_factory=dict)
    delete_t: float | None = None
    first_updated: datetime | None = None
    last_updated: datetime | None = None

    @staticmethod
    def from_event(e: Event) -> "EventOp":
        op = EventOp()
        t = _millis(e.event_time)
        if e.event == "$set":
            op.set_fields = {k: _PropTime(v, t) for k, v in e.properties.items()}
            op.set_t = t
        elif e.event == "$unset":
            op.unset_fields = {k: t for k in e.properties.key_set()}
        elif e.event == "$delete":
            op.delete_t = t
        else:
            return op  # non-special events do not touch properties
        op.first_updated = e.event_time
        op.last_updated = e.event_time
        return op

    def merge(self, other: "EventOp") -> "EventOp":
        out = EventOp()
        # $set: per-key last-write-wins; ties broken deterministically on the
        # serialized value so merge stays commutative even at equal timestamps
        # (bulk imports often stamp a whole batch with one eventTime)
        out.set_fields = dict(self.set_fields)
        for k, pt in other.set_fields.items():
            cur = out.set_fields.get(k)
            if cur is None or pt.t > cur.t or (
                pt.t == cur.t and _value_key(pt.value) > _value_key(cur.value)
            ):
                out.set_fields[k] = pt
        out.set_t = _max_opt(self.set_t, other.set_t)
        # $unset: latest unset time per key
        out.unset_fields = dict(self.unset_fields)
        for k, t in other.unset_fields.items():
            out.unset_fields[k] = max(t, out.unset_fields.get(k, float("-inf")))
        out.delete_t = _max_opt(self.delete_t, other.delete_t)
        out.first_updated = _min_opt_dt(self.first_updated, other.first_updated)
        out.last_updated = _max_opt_dt(self.last_updated, other.last_updated)
        return out

    def to_property_map(self) -> PropertyMap | None:
        """Resolve the monoid into the final entity state (reference
        PEventAggregator.scala:150-190 ``toPropertyMap``)."""
        if self.set_t is None:
            return None
        if self.delete_t is not None and self.delete_t >= self.set_t:
            # entity deleted after (or at) the last $set
            return None
        fields: dict[str, Any] = {}
        for k, pt in self.set_fields.items():
            if self.delete_t is not None and self.delete_t >= pt.t:
                continue
            unset_t = self.unset_fields.get(k)
            if unset_t is not None and unset_t >= pt.t:
                continue
            fields[k] = pt.value
        assert self.first_updated is not None and self.last_updated is not None
        return PropertyMap(fields, self.first_updated, self.last_updated)


def _value_key(v: Any) -> str:
    import json

    return json.dumps(v, sort_keys=True, default=str)


def _max_opt(a: float | None, b: float | None) -> float | None:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt_dt(a: datetime | None, b: datetime | None) -> datetime | None:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt_dt(a: datetime | None, b: datetime | None) -> datetime | None:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def aggregate_properties(events: Iterable[Event]) -> dict[str, PropertyMap]:
    """Fold special events into per-entity PropertyMaps
    (reference LEventAggregator.aggregateProperties, LEventAggregator.scala:24-44).
    Entities whose final state is deleted/never-set are dropped."""
    ops: dict[str, EventOp] = {}
    for e in events:
        if e.event not in ("$set", "$unset", "$delete"):
            continue
        op = EventOp.from_event(e)
        prev = ops.get(e.entity_id)
        ops[e.entity_id] = op if prev is None else prev.merge(op)
    out: dict[str, PropertyMap] = {}
    for entity_id, op in ops.items():
        pm = op.to_property_map()
        if pm is not None:
            out[entity_id] = pm
    return out


def aggregate_properties_single(events: Iterator[Event]) -> PropertyMap | None:
    """Single-entity variant (LEventAggregator.scala:46-64)."""
    acc: EventOp | None = None
    for e in events:
        if e.event not in ("$set", "$unset", "$delete"):
            continue
        op = EventOp.from_event(e)
        acc = op if acc is None else acc.merge(op)
    return acc.to_property_map() if acc is not None else None


def aggregate_properties_frame(frame) -> dict[str, PropertyMap]:
    """Columnar-input fold: ``aggregate_properties`` over an
    ``EventFrame`` (ISSUE 9, the train-side read pushdown).

    The pre-pass is vectorized — mask the special events, one stable
    numpy argsort groups each entity's rows contiguously, boundary
    detection yields the per-entity segments — so the Python loop runs
    once per ENTITY over plain floats/dicts instead of once per EVENT
    over ``Event``/``EventOp`` objects. The per-segment accumulation is
    the ``EventOp`` monoid inlined: identical comparisons (per-key
    last-write-wins with the ``_value_key`` tie-break, latest ``$unset``
    per key, latest ``$delete``, min/max updated times), so the result
    is bit-identical to folding ``EventOp.from_event``/``merge`` — the
    parity tests in tests/test_aggregate.py pin that.
    """
    if len(frame) == 0:
        return {}
    names = frame.event
    mask = (names == "$set") | (names == "$unset") | (names == "$delete")
    idx = np.nonzero(mask)[0]
    if idx.size == 0:
        return {}
    ids = frame.entity_id[idx]
    order = np.argsort(ids, kind="stable")
    sel = idx[order]
    sorted_ids = ids[order]
    bounds = np.nonzero(sorted_ids[1:] != sorted_ids[:-1])[0] + 1
    starts = np.concatenate(([0], bounds))
    ends = np.concatenate((bounds, [sorted_ids.size]))
    # plain-list views: per-event numpy scalar indexing in the fold loop
    # costs more than the fold itself at 200k events
    sel_l = sel.tolist()
    names_l = names.tolist()
    times_l = frame.event_time.tolist()
    props = frame.properties
    out: dict[str, PropertyMap] = {}
    inf = float("inf")
    for s0, s1 in zip(starts.tolist(), ends.tolist()):
        set_fields: dict[str, tuple[float, Any]] = {}  # k -> (t, value)
        set_t: float | None = None
        unset: dict[str, float] = {}
        delete_t: float | None = None
        first_t, last_t = inf, -inf
        for j in sel_l[s0:s1]:
            name = names_l[j]
            t = times_l[j]
            if name == "$set":
                for k, v in props[j].items():
                    cur = set_fields.get(k)
                    if cur is None or t > cur[0] or (
                        t == cur[0] and _value_key(v) > _value_key(cur[1])
                    ):
                        set_fields[k] = (t, v)
                set_t = t if set_t is None else max(set_t, t)
            elif name == "$unset":
                for k in props[j]:
                    prev = unset.get(k)
                    unset[k] = t if prev is None else max(prev, t)
            else:  # $delete
                delete_t = t if delete_t is None else max(delete_t, t)
            if t < first_t:
                first_t = t
            if t > last_t:
                last_t = t
        if set_t is None or (delete_t is not None and delete_t >= set_t):
            continue  # never set, or deleted after the last $set
        fields: dict[str, Any] = {}
        for k, (t, v) in set_fields.items():
            if delete_t is not None and delete_t >= t:
                continue
            ut = unset.get(k)
            if ut is not None and ut >= t:
                continue
            fields[k] = v
        out[sorted_ids[s0]] = PropertyMap(
            fields,
            datetime.fromtimestamp(first_t, tz=timezone.utc),
            datetime.fromtimestamp(last_t, tz=timezone.utc))
    return out
