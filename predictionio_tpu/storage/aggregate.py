"""Property aggregation: folding ``$set``/``$unset``/``$delete`` event
streams into per-entity ``PropertyMap``s.

Reference semantics (data/src/main/scala/io/prediction/data/storage/
LEventAggregator.scala:22-123 and PEventAggregator.scala:35-209):

- events are processed in ``event_time`` order per entity;
- ``$set`` merges properties (later wins per key);
- ``$unset`` removes the named keys (only if the entity currently exists);
- ``$delete`` erases the entity (it may be re-created by a later ``$set``);
- entities whose fold ends with no live DataMap are dropped;
- first/last updated times span all special events seen for the entity.

The parallel version in the reference is an ``aggregateByKey`` over a
commutative monoid (``EventOp ++``). Here the same monoid is implemented so
aggregation can run as an associative merge over event shards — the
host-side analog of a segment reduce — and is therefore safe to parallelize
over processes or to fold incrementally as events stream in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Iterable, Iterator

from .datamap import PropertyMap
from .event import Event

__all__ = ["EventOp", "aggregate_properties", "aggregate_properties_single"]


def _millis(t: datetime) -> float:
    return t.timestamp()


@dataclass
class _PropTime:
    value: Any
    t: float


@dataclass
class EventOp:
    """The aggregation monoid (reference PEventAggregator.scala:95-190).

    Tracks, independently: last-write-wins ``$set`` fields, latest ``$unset``
    time per key, latest ``$delete`` time, and first/last updated times.
    ``merge`` is associative and commutative, so shard-level partial
    aggregates combine in any order.
    """

    set_fields: dict[str, _PropTime] = field(default_factory=dict)
    set_t: float | None = None  # latest $set time (fields may be empty)
    unset_fields: dict[str, float] = field(default_factory=dict)
    delete_t: float | None = None
    first_updated: datetime | None = None
    last_updated: datetime | None = None

    @staticmethod
    def from_event(e: Event) -> "EventOp":
        op = EventOp()
        t = _millis(e.event_time)
        if e.event == "$set":
            op.set_fields = {k: _PropTime(v, t) for k, v in e.properties.items()}
            op.set_t = t
        elif e.event == "$unset":
            op.unset_fields = {k: t for k in e.properties.key_set()}
        elif e.event == "$delete":
            op.delete_t = t
        else:
            return op  # non-special events do not touch properties
        op.first_updated = e.event_time
        op.last_updated = e.event_time
        return op

    def merge(self, other: "EventOp") -> "EventOp":
        out = EventOp()
        # $set: per-key last-write-wins; ties broken deterministically on the
        # serialized value so merge stays commutative even at equal timestamps
        # (bulk imports often stamp a whole batch with one eventTime)
        out.set_fields = dict(self.set_fields)
        for k, pt in other.set_fields.items():
            cur = out.set_fields.get(k)
            if cur is None or pt.t > cur.t or (
                pt.t == cur.t and _value_key(pt.value) > _value_key(cur.value)
            ):
                out.set_fields[k] = pt
        out.set_t = _max_opt(self.set_t, other.set_t)
        # $unset: latest unset time per key
        out.unset_fields = dict(self.unset_fields)
        for k, t in other.unset_fields.items():
            out.unset_fields[k] = max(t, out.unset_fields.get(k, float("-inf")))
        out.delete_t = _max_opt(self.delete_t, other.delete_t)
        out.first_updated = _min_opt_dt(self.first_updated, other.first_updated)
        out.last_updated = _max_opt_dt(self.last_updated, other.last_updated)
        return out

    def to_property_map(self) -> PropertyMap | None:
        """Resolve the monoid into the final entity state (reference
        PEventAggregator.scala:150-190 ``toPropertyMap``)."""
        if self.set_t is None:
            return None
        if self.delete_t is not None and self.delete_t >= self.set_t:
            # entity deleted after (or at) the last $set
            return None
        fields: dict[str, Any] = {}
        for k, pt in self.set_fields.items():
            if self.delete_t is not None and self.delete_t >= pt.t:
                continue
            unset_t = self.unset_fields.get(k)
            if unset_t is not None and unset_t >= pt.t:
                continue
            fields[k] = pt.value
        assert self.first_updated is not None and self.last_updated is not None
        return PropertyMap(fields, self.first_updated, self.last_updated)


def _value_key(v: Any) -> str:
    import json

    return json.dumps(v, sort_keys=True, default=str)


def _max_opt(a: float | None, b: float | None) -> float | None:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt_dt(a: datetime | None, b: datetime | None) -> datetime | None:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt_dt(a: datetime | None, b: datetime | None) -> datetime | None:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def aggregate_properties(events: Iterable[Event]) -> dict[str, PropertyMap]:
    """Fold special events into per-entity PropertyMaps
    (reference LEventAggregator.aggregateProperties, LEventAggregator.scala:24-44).
    Entities whose final state is deleted/never-set are dropped."""
    ops: dict[str, EventOp] = {}
    for e in events:
        if e.event not in ("$set", "$unset", "$delete"):
            continue
        op = EventOp.from_event(e)
        prev = ops.get(e.entity_id)
        ops[e.entity_id] = op if prev is None else prev.merge(op)
    out: dict[str, PropertyMap] = {}
    for entity_id, op in ops.items():
        pm = op.to_property_map()
        if pm is not None:
            out[entity_id] = pm
    return out


def aggregate_properties_single(events: Iterator[Event]) -> PropertyMap | None:
    """Single-entity variant (LEventAggregator.scala:46-64)."""
    acc: EventOp | None = None
    for e in events:
        if e.event not in ("$set", "$unset", "$delete"):
            continue
        op = EventOp.from_event(e)
        acc = op if acc is None else acc.merge(op)
    return acc.to_property_map() if acc is not None else None
