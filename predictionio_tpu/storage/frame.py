"""Columnar event batches — the TPU-feeding representation.

The reference moves events through training as ``RDD[Event]`` (JVM objects
shuffled between executors). A TPU framework wants events as contiguous
columns: ids reindexed to dense ints, times as float64 epochs, so a whole
training read is a handful of numpy arrays that ``jax.device_put`` can lay
out across a mesh in one call. ``EventFrame`` is that representation;
``frame.to_ratings()`` is the one-liner that replaces the reference
templates' per-event ``map``s (e.g. examples/scala-parallel-recommendation/
custom-serving/src/main/scala/DataSource.scala:25-54).
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .bimap import BiMap
from .datamap import DataMap
from .event import Event

__all__ = ["EventFrame", "Ratings"]


@dataclass
class Ratings:
    """Dense-indexed (user, item, rating) triples plus the id maps —
    ready for sharded COO construction in the ALS path."""

    user_indices: np.ndarray  # int32 [n]
    item_indices: np.ndarray  # int32 [n]
    ratings: np.ndarray  # float32 [n]
    user_ids: BiMap  # str -> int
    item_ids: BiMap  # str -> int

    @property
    def num_users(self) -> int:
        return len(self.user_ids)

    @property
    def num_items(self) -> int:
        return len(self.item_ids)

    def __len__(self) -> int:
        return int(self.ratings.shape[0])

    @classmethod
    def from_triples(cls, users: Sequence[str], items: Sequence[str],
                     ratings: Sequence[float]) -> "Ratings":
        """String-id (user, item, rating) triples -> dense-indexed
        Ratings — the custom-datasource entry point (the reference's
        BiMap.stringInt reindex, BiMap.scala:72-126, for data that never
        went through the event store). Same vectorized reindex as
        ``EventFrame.to_ratings``."""
        u_map, uidx = BiMap.from_array(np.asarray(users, dtype=object))
        i_map, iidx = BiMap.from_array(np.asarray(items, dtype=object))
        return cls(
            user_indices=uidx.astype(np.int64),
            item_indices=iidx.astype(np.int64),
            ratings=np.asarray(ratings, np.float32),
            user_ids=u_map,
            item_ids=i_map,
        )


class EventFrame:
    """A batch of events in columnar (struct-of-arrays) form.

    String columns are object-dtype numpy arrays (zero-copy slicing,
    vectorized ``np.unique`` reindexing); times are float64 UTC epoch
    seconds; properties stay as a list of dicts (only touched by
    property-reading paths, which are not hot).
    """

    __slots__ = ("event", "entity_type", "entity_id", "target_entity_type",
                 "target_entity_id", "event_time", "properties")

    def __init__(
        self,
        event: np.ndarray,
        entity_type: np.ndarray,
        entity_id: np.ndarray,
        target_entity_type: np.ndarray,
        target_entity_id: np.ndarray,
        event_time: np.ndarray,
        properties: list[dict[str, Any]],
    ):
        self.event = event
        self.entity_type = entity_type
        self.entity_id = entity_id
        self.target_entity_type = target_entity_type
        self.target_entity_id = target_entity_id
        self.event_time = event_time
        self.properties = properties

    def __len__(self) -> int:
        return int(self.event.shape[0])

    @staticmethod
    def from_events(events: Iterable[Event]) -> "EventFrame":
        ev, et, ei, tt, ti, tm, pr = [], [], [], [], [], [], []
        for e in events:
            ev.append(e.event)
            et.append(e.entity_type)
            ei.append(e.entity_id)
            tt.append(e.target_entity_type)
            ti.append(e.target_entity_id)
            tm.append(e.event_time.timestamp())
            pr.append(e.properties.to_dict())
        return EventFrame(
            event=np.asarray(ev, dtype=object),
            entity_type=np.asarray(et, dtype=object),
            entity_id=np.asarray(ei, dtype=object),
            target_entity_type=np.asarray(tt, dtype=object),
            target_entity_id=np.asarray(ti, dtype=object),
            event_time=np.asarray(tm, dtype=np.float64),
            properties=pr,
        )

    def to_events(self) -> list[Event]:
        out = []
        for i in range(len(self)):
            out.append(
                Event(
                    event=self.event[i],
                    entity_type=self.entity_type[i],
                    entity_id=self.entity_id[i],
                    target_entity_type=self.target_entity_type[i],
                    target_entity_id=self.target_entity_id[i],
                    properties=DataMap.from_dict(self.properties[i]),
                    event_time=datetime.fromtimestamp(
                        float(self.event_time[i]), tz=timezone.utc
                    ),
                )
            )
        return out

    def select(self, mask: np.ndarray) -> "EventFrame":
        idx = np.nonzero(mask)[0]
        return EventFrame(
            event=self.event[idx],
            entity_type=self.entity_type[idx],
            entity_id=self.entity_id[idx],
            target_entity_type=self.target_entity_type[idx],
            target_entity_id=self.target_entity_id[idx],
            event_time=self.event_time[idx],
            properties=[self.properties[i] for i in idx],
        )

    def where_event(self, names: Sequence[str]) -> "EventFrame":
        return self.select(np.isin(self.event, list(names)))

    # -- dense reindexing (the BiMap/ALS path) ----------------------------
    def to_ratings(
        self,
        rating_of: Callable[[str, dict[str, Any]], float | None] | None = None,
        user_ids: BiMap | None = None,
        item_ids: BiMap | None = None,
        dedup_latest: bool = True,
    ) -> Ratings:
        """Vectorized events -> dense-indexed rating triples.

        ``rating_of(event_name, properties)`` returns the rating value or
        None to skip the event (default: ``properties["rating"]`` for
        "rate" events, 1.0 otherwise — the recommendation template's rule,
        reference DataSource.scala:31-49). When ``dedup_latest`` is set,
        duplicate (user, item) pairs keep the latest-by-event-time value
        (reference MLlibRating dedup in templates).
        """
        if rating_of is None:
            def rating_of(name: str, props: dict[str, Any]) -> float | None:
                if name == "rate":
                    v = props.get("rating")
                    return float(v) if v is not None else None
                return 1.0

        vals = np.empty(len(self), dtype=np.float64)
        keep = np.zeros(len(self), dtype=bool)
        for i in range(len(self)):
            if self.target_entity_id[i] is None:
                continue  # no target entity => not a (user, item) interaction
            r = rating_of(self.event[i], self.properties[i])
            if r is not None:
                vals[i] = r
                keep[i] = True
        idx = np.nonzero(keep)[0]
        users = self.entity_id[idx]
        items = self.target_entity_id[idx]
        times = self.event_time[idx]
        vals = vals[idx]

        if user_ids is None:
            user_ids, uidx = BiMap.from_array(users)
        else:
            uidx = user_ids.map_array(list(users))
        if item_ids is None:
            item_ids, iidx = BiMap.from_array(items)
        else:
            iidx = item_ids.map_array(list(items))
        valid = (uidx >= 0) & (iidx >= 0)
        uidx, iidx, vals, times = uidx[valid], iidx[valid], vals[valid], times[valid]

        if dedup_latest and len(vals):
            # stable sort by (pair, time); keep last per pair
            pair = uidx.astype(np.int64) * len(item_ids) + iidx
            order = np.lexsort((times, pair))
            pair_sorted = pair[order]
            last = np.ones(len(order), dtype=bool)
            last[:-1] = pair_sorted[1:] != pair_sorted[:-1]
            sel = order[last]
            sel.sort()
            uidx, iidx, vals = uidx[sel], iidx[sel], vals[sel]

        return Ratings(
            user_indices=uidx.astype(np.int32),
            item_indices=iidx.astype(np.int32),
            ratings=vals.astype(np.float32),
            user_ids=user_ids,
            item_ids=item_ids,
        )
