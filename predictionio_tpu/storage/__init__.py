"""Storage layer: events, metadata, models — the L1 of the framework.

Mirrors the capability of the reference's ``data/.../storage`` package
(Storage SPI + HBase/ES/MongoDB/localfs backends) with in-process,
sqlite and filesystem backends behind the same repository registry.
"""

from .aggregate import (EventOp, aggregate_properties,
                        aggregate_properties_frame,
                        aggregate_properties_single)
from .bimap import BiMap, string_int_bimap
from .datamap import DataMap, DataMapError, PropertyMap
from .event import (
    Event,
    SPECIAL_EVENTS,
    ValidationError,
    event_from_api_dict,
    event_from_json,
    event_to_api_dict,
    event_to_json,
    validate_event,
)
from .events_base import ANY, EventBackend, EventQuery, StorageError, TableNotInitialized
from .frame import EventFrame, Ratings
# NOTE: .journal is intentionally NOT imported here — it fires chaos
# sites through workflow.faults, and workflow imports this package.
# Import it as `predictionio_tpu.storage.journal` (the api layer does).
from .memory import MemoryEvents
from .partition import entity_key, hash64, iter_host_shard, partition_events, shard_of
from .metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    MetadataStore,
    Model,
)
from .registry import Storage
from .sqlite import SQLiteEvents

__all__ = [
    "ANY", "AccessKey", "App", "BiMap", "Channel", "DataMap", "DataMapError",
    "EngineInstance", "EngineManifest", "EvaluationInstance", "Event",
    "EventBackend", "EventFrame", "EventOp", "EventQuery",
    "MemoryEvents", "MetadataStore", "Model", "PropertyMap",
    "Ratings", "SPECIAL_EVENTS", "SQLiteEvents", "Storage", "StorageError",
    "TableNotInitialized", "ValidationError",
    "aggregate_properties", "aggregate_properties_frame",
    "aggregate_properties_single",
    "event_from_api_dict", "event_from_json", "event_to_api_dict",
    "entity_key", "hash64", "iter_host_shard", "partition_events", "shard_of",
    "event_to_json", "string_int_bimap", "validate_event",
]
