"""Environment-driven storage registry.

The analog of the reference ``Storage`` object (reference: data/src/main/
scala/io/prediction/data/storage/Storage.scala:40-312): sources are declared
via ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ per-type config vars) and the
three repositories (METADATA, EVENTDATA, MODELDATA) are bound to sources
via ``PIO_STORAGE_REPOSITORIES_<REPO>_{NAME,SOURCE}``.

Built-in source types:

- ``memory``  — in-process (tests, quickstart)
- ``sqlite``  — durable single file; config var ``PIO_STORAGE_SOURCES_<N>_PATH``
- ``localfs`` — model blobs on the filesystem; config var ``..._PATH``

Defaults (no env vars set): everything under ``$PIO_HOME`` (or
``~/.predictionio_tpu``) in sqlite/localfs — durable out of the box.
Set ``PIO_STORAGE_SOURCES_*`` to swap backends without touching code,
exactly like the reference's pio-env.sh (conf/pio-env.sh.template).
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Any

from .events_base import EventBackend, StorageError
from .memory import MemoryEvents
from .metadata import MetadataStore, Model
from .sqlite import SQLiteEvents

__all__ = ["Storage", "StorageError"]

_REPOS = ("METADATA", "EVENTDATA", "MODELDATA")


class LocalFSModels:
    """Model blobs as files in a directory (reference: data/.../storage/
    localfs/LocalFSModels.scala)."""

    def __init__(self, path: str):
        self._dir = Path(path)
        self._dir.mkdir(parents=True, exist_ok=True)

    def insert(self, m: Model) -> None:
        (self._dir / m.id).write_bytes(m.models)
        # integrity sidecar — the file-backed analog of the sqlite
        # checksum column; absent for pre-integrity blobs
        sidecar = self._dir / f"{m.id}.sha256"
        if m.checksum:
            sidecar.write_text(m.checksum)
        elif sidecar.exists():
            sidecar.unlink()

    def get(self, id: str) -> Model | None:
        p = self._dir / id
        if not p.exists():
            return None
        sidecar = self._dir / f"{id}.sha256"
        checksum = sidecar.read_text().strip() if sidecar.exists() else ""
        return Model(id=id, models=p.read_bytes(), checksum=checksum)

    def delete(self, id: str) -> bool:
        p = self._dir / id
        sidecar = self._dir / f"{id}.sha256"
        if sidecar.exists():
            sidecar.unlink()
        if p.exists():
            p.unlink()
            return True
        return False


class _SQLiteModels:
    def __init__(self, meta: MetadataStore):
        self._meta = meta

    def insert(self, m: Model) -> None:
        self._meta.model_insert(m)

    def get(self, id: str) -> Model | None:
        return self._meta.model_get(id)

    def delete(self, id: str) -> bool:
        return self._meta.model_delete(id)


class Storage:
    """Process-wide registry. ``Storage.get_*()`` lazily builds clients from
    the environment; ``Storage.configure()`` overrides programmatically
    (used by tests and by in-process servers)."""

    _lock = threading.RLock()
    _instances: dict[str, Any] = {}
    _overrides: dict[str, dict[str, Any]] = {}

    # -- configuration ----------------------------------------------------
    @classmethod
    def home(cls) -> Path:
        return Path(os.environ.get("PIO_HOME", str(Path.home() / ".predictionio_tpu")))

    @classmethod
    def configure(cls, repo: str, type: str, **config: Any) -> None:
        """Programmatic override: Storage.configure("EVENTDATA", "memory")."""
        with cls._lock:
            cls._overrides[repo.upper()] = {"type": type, **config}
            cls._instances.pop(repo.upper(), None)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            for inst in cls._instances.values():
                close = getattr(inst, "close", None)
                if close:
                    try:
                        close()
                    except Exception:
                        pass
            cls._instances.clear()
            cls._overrides.clear()

    @classmethod
    def _repo_config(cls, repo: str) -> dict[str, Any]:
        if repo in cls._overrides:
            return dict(cls._overrides[repo])
        source = os.environ.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
        if source:
            typ = os.environ.get(f"PIO_STORAGE_SOURCES_{source}_TYPE")
            if not typ:
                raise StorageError(
                    f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE={source} but "
                    f"PIO_STORAGE_SOURCES_{source}_TYPE is not set"
                )
            cfg: dict[str, Any] = {"type": typ.lower()}
            prefix = f"PIO_STORAGE_SOURCES_{source}_"
            for k, v in os.environ.items():
                if k.startswith(prefix) and k != prefix + "TYPE":
                    cfg[k[len(prefix):].lower()] = v
            return cfg
        # defaults: durable sqlite/localfs under PIO_HOME
        home = cls.home()
        if repo == "METADATA":
            return {"type": "sqlite", "path": str(home / "metadata.db")}
        if repo == "EVENTDATA":
            return {"type": "sqlite", "path": str(home / "events.db")}
        return {"type": "localfs", "path": str(home / "models")}

    # -- accessors --------------------------------------------------------
    @classmethod
    def _get(cls, repo: str) -> Any:
        with cls._lock:
            if repo in cls._instances:
                return cls._instances[repo]
            cfg = cls._repo_config(repo)
            typ = cfg.pop("type")
            inst = cls._build(repo, typ, cfg)
            cls._instances[repo] = inst
            return inst

    @classmethod
    def _build(cls, repo: str, typ: str, cfg: dict[str, Any]) -> Any:
        if repo == "EVENTDATA":
            if typ == "memory":
                return MemoryEvents(cfg)
            if typ == "sqlite":
                _mkparent(cfg.get("path"))
                return SQLiteEvents(cfg)
            raise StorageError(f"unknown EVENTDATA source type: {typ}")
        if repo == "METADATA":
            if typ == "memory":
                return MetadataStore(":memory:")
            if typ == "sqlite":
                path = cfg.get("path", ":memory:")
                _mkparent(path)
                return MetadataStore(path)
            raise StorageError(f"unknown METADATA source type: {typ}")
        if repo == "MODELDATA":
            if typ == "localfs":
                return LocalFSModels(cfg.get("path", str(cls.home() / "models")))
            if typ == "memory":
                return _SQLiteModels(cls.get_metadata())
            if typ == "sqlite":
                path = cfg.get("path")
                if path:
                    _mkparent(path)
                    return _SQLiteModels(MetadataStore(path))
                return _SQLiteModels(cls.get_metadata())
            raise StorageError(f"unknown MODELDATA source type: {typ}")
        raise StorageError(f"unknown repository {repo}")

    @classmethod
    def get_metadata(cls) -> MetadataStore:
        return cls._get("METADATA")

    @classmethod
    def get_events(cls) -> EventBackend:
        return cls._get("EVENTDATA")

    @classmethod
    def get_models(cls):
        return cls._get("MODELDATA")

    # -- pio status (Storage.verifyAllDataObjects, Storage.scala:237-257) --
    @classmethod
    def verify_all_data_objects(cls) -> dict[str, str]:
        out: dict[str, str] = {}
        for repo in _REPOS:
            try:
                cls._get(repo)
                out[repo] = "ok"
            except Exception as e:  # noqa: BLE001 — status report, not control flow
                out[repo] = f"error: {e}"
        return out


def _mkparent(path: str | None) -> None:
    if path and path != ":memory:":
        Path(path).parent.mkdir(parents=True, exist_ok=True)
