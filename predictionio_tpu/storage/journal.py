"""Segmented append-only event journal — the ingestion write-ahead log.

The reference's HBase event backend gave ingestion a real WAL for free
(every `put` lands in the RegionServer's HLog before it is acked); the
sqlite/memory backends here have nothing between "201 sent" and "row
committed", so a storage outage turns every POST into a 500 and a crash
loses whatever was in flight. This module restores the missing layer:
the event server appends each accepted event to this journal, fsyncs per
its policy, and acks 201 — a background drainer then pushes journal
records into the ``EventBackend`` at its own pace (api/ingest.py).

Design (the classic single-writer log, cf. HLog / Kafka segment logs):

- **Segments**: ``journal-<seq>.log`` files under one directory; the
  active segment rotates at ``segment_max_bytes`` so drained history can
  be garbage-collected file-at-a-time instead of compacted in place.
- **Framing**: each record is ``<u32 length><u32 crc32(payload)>`` +
  payload (little-endian). CRC + length make a torn write detectable.
- **Torn-tail truncation**: a crash mid-append leaves a partial frame at
  the tail. On open, every segment is scanned; the first invalid frame
  truncates its segment there and drops any later segments — recovery
  keeps the longest valid prefix, never a hole.
- **Cursor**: the drainer's progress ``(segment, offset, index)`` is
  persisted atomically (tmp + ``os.replace``) in ``cursor.json``;
  segments wholly behind the cursor are deleted. After a crash the
  drainer resumes from the last persisted cursor — records drained but
  not yet cursored are re-pushed, which is safe because event ids are
  assigned BEFORE journaling and both built-in backends upsert by id
  (``INSERT OR REPLACE``): replay is idempotent.
- **fsync policy**: ``always`` (fsync inside every append), ``batch``
  (the caller fsyncs once per ingest request via ``sync()`` before
  acking), ``never`` (leave durability to the OS page cache — survives a
  process crash, not a power cut).
- **Backpressure**: past ``max_bytes`` of un-collected segments,
  ``append`` raises ``JournalFull`` — the server turns that into 503 +
  ``Retry-After`` instead of silently dropping events.

Chaos sites: ``journal.append`` fires at the head of every append,
``journal.fsync`` before every fsync, and ``journal.partition_append``
at the head of every routed ``PartitionedJournal.append``
(workflow/faults.py), so disk-level failures are provable in tests
without a broken disk.

Thread-safety: one lock around all mutation; appends come from the event
server's ``asyncio.to_thread`` workers while the drainer reads/advances
from its own thread.

**Partitioning** (``PartitionedJournal``): the reference scaled ingest by
letting HBase split the event table across region servers by row-key
hash (``HBEventsUtil.RowKey`` = hash(entity) prefix); the analog here is
N independent ``EventJournal`` instances keyed by
``shard_of(entity_type, entity_id, N)`` (storage/partition.py — the same
hash the trainer shards by). Each partition has its own segments,
cursor, fsync batch, GC and fill fraction under ``p<k>/``; ``N == 1``
keeps the original flat single-directory layout, byte-compatible with
journals written before partitioning existed. Global ordering weakens to
per-entity ordering — all that training and ``aggregate_properties``
ever relied on. A ``partitions.json`` marker stamps the layout; opening
with a different N is a **resize** and is refused unless every old
partition is fully drained (see docs/operations.md "Ingestion at
scale").
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import struct
import threading
import time
import zlib
from pathlib import Path

from ..obs.metrics import METRICS
from ..workflow.faults import FAULTS

# ISSUE 5: journal durability costs, scrapeable (the stats() dict keeps
# its raw-counter shape; these add the latency distributions)
_M_APPEND = METRICS.histogram(
    "pio_journal_append_seconds",
    "EventJournal.append wall time (frame + write + policy fsync)")
_M_FSYNC = METRICS.histogram(
    "pio_journal_fsync_seconds",
    "journal fsync wall time (the durability floor of a 201 ack)")
# ISSUE 9: per-partition surfaces — a hot or wedged partition must be
# visible as ITSELF, not averaged away in the totals
_M_PART_LAG = METRICS.gauge(
    "pio_journal_partition_lag",
    "undrained records in one journal partition",
    labelnames=("partition",))
_M_PART_FILL = METRICS.gauge(
    "pio_journal_partition_fill",
    "fill fraction (sizeBytes/maxBytes) of one journal partition",
    labelnames=("partition",))

log = logging.getLogger("predictionio_tpu.journal")

__all__ = ["EventJournal", "PartitionedJournal", "JournalFollower",
           "JournalFull", "JournalLayoutError", "FSYNC_POLICIES",
           "iter_journal_records"]

_HEADER = struct.Struct("<II")  # (payload length, crc32(payload))
_SEGMENT_GLOB = "journal-*.log"
_CURSOR_FILE = "cursor.json"
_PARTITIONS_FILE = "partitions.json"

FSYNC_POLICIES = ("always", "batch", "never")


class JournalFull(RuntimeError):
    """The journal hit ``max_bytes`` of undrained data — the caller must
    shed load (503 + Retry-After) instead of dropping the event."""


class JournalLayoutError(RuntimeError):
    """The on-disk partition layout does not match the requested
    partition count and at least one old partition still holds undrained
    records. Resizing N -> M requires drained journals (stop ingest, let
    the drainers reach lag 0, restart with the new count) — re-hashing
    undrained records across a different N would break per-entity
    ordering and exactly-once replay."""


def _layout_of(directory: Path) -> int | None:
    """Partition count of whatever lives in ``directory``: the stamped
    ``partitions.json`` marker if readable, else inferred from the files
    (p<k>/ subdirs, or flat pre-partitioning segments -> 1). Shared by
    the writer (``PartitionedJournal``) and read-only followers."""
    try:
        n = int(json.loads(
            (directory / _PARTITIONS_FILE).read_text())["partitions"])
        if n >= 1:
            return n
    except FileNotFoundError:
        pass
    except (json.JSONDecodeError, ValueError, KeyError, TypeError,
            OSError) as e:
        log.warning("journal: unreadable %s (%s); inferring layout "
                    "from files", _PARTITIONS_FILE, e)
    pdirs = [d for d in directory.glob("p*")
             if d.is_dir() and d.name[1:].isdigit()]
    if pdirs:
        return max(int(d.name[1:]) for d in pdirs) + 1
    if any(directory.glob(_SEGMENT_GLOB)) \
            or (directory / _CURSOR_FILE).exists():
        return 1
    return None


def _segment_name(seq: int) -> str:
    return f"journal-{seq:08d}.log"


def _segment_seq(path: Path) -> int:
    return int(path.name[len("journal-"):-len(".log")])


def iter_journal_records(directory: str | os.PathLike):
    """Yield every valid record payload under ``directory``, oldest
    first — a pure read-only scan (ISSUE 13: the capture/replay layer's
    view of a capture journal). Unlike ``JournalFollower`` this carries
    no cursor at all: every segment's longest valid record prefix is
    read in seq order, torn tails and vanished segments are skipped
    (never fatal), and nothing on disk is touched."""
    for path in sorted(Path(directory).glob(_SEGMENT_GLOB),
                       key=_segment_seq):
        try:
            with open(path, "rb") as fh:
                while True:
                    header = fh.read(_HEADER.size)
                    if len(header) < _HEADER.size:
                        break
                    length, crc = _HEADER.unpack(header)
                    payload = fh.read(length)
                    if len(payload) < length or zlib.crc32(payload) != crc:
                        break  # torn tail: keep the valid prefix only
                    yield payload
        except OSError:
            continue  # segment GC'd mid-scan: the rest still reads


class _Segment:
    """One on-disk segment: its seq, path, logical size and record count.

    ``size`` is the VALID byte length (post torn-tail truncation) — the
    reader never reads past it, the writer only appends at it."""

    __slots__ = ("seq", "path", "size", "records")

    def __init__(self, seq: int, path: Path, size: int = 0, records: int = 0):
        self.seq = seq
        self.path = path
        self.size = size
        self.records = records


class EventJournal:
    """Crash-safe append-only record log with a persisted drain cursor."""

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        fsync: str = "batch",
        max_bytes: int = 256 * 1024 * 1024,
        segment_max_bytes: int = 16 * 1024 * 1024,
    ):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy must be one of {FSYNC_POLICIES}, got {fsync!r}")
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fsync_policy = fsync
        self.max_bytes = max(1, int(max_bytes))
        self.segment_max_bytes = max(1, int(segment_max_bytes))
        self._lock = threading.Lock()
        self._closed = False
        self._segments: list[_Segment] = []
        self._write_fh = None  # open append handle on the LAST segment
        # drain cursor: next record to hand the drainer
        self._drain_seq = 0
        self._drain_off = 0
        self._drain_idx = 0  # monotonically increasing global record index
        self._undrained = 0
        # counters (stats()/health surfaces)
        self.appended = 0          # records appended this process
        self.drained = 0           # records acked past the cursor this process
        self.synced = 0            # fsync calls
        self.unsynced_bytes = 0    # bytes appended since the last fsync
        self.truncated_bytes = 0   # torn-tail bytes dropped at open
        self.rotations = 0
        self.segments_removed = 0
        self._recover()

    # -- recovery ----------------------------------------------------------
    def _recover(self) -> None:
        """Scan segments, truncate the torn tail, load the cursor, GC
        fully-drained history."""
        paths = sorted(self.dir.glob(_SEGMENT_GLOB), key=_segment_seq)
        torn = False
        for path in paths:
            if torn:
                # a bad frame invalidates everything after it: keep the
                # longest valid prefix, never a prefix with a hole
                log.warning("journal: dropping segment %s after torn tail",
                            path.name)
                self.truncated_bytes += path.stat().st_size
                path.unlink()
                continue
            seg = _Segment(_segment_seq(path), path)
            valid, records = self._scan_segment(path)
            raw = path.stat().st_size
            if valid < raw:
                log.warning(
                    "journal: truncating torn tail of %s at %d (%d bytes "
                    "dropped)", path.name, valid, raw - valid)
                with open(path, "rb+") as fh:
                    fh.truncate(valid)
                    fh.flush()
                    os.fsync(fh.fileno())
                self.truncated_bytes += raw - valid
                torn = True
            seg.size = valid
            seg.records = records
            self._segments.append(seg)
        cursor = self._load_cursor()
        if not self._segments:
            # nothing on disk (fresh dir, or everything drained + GC'd
            # before the restart): start one segment PAST the cursored
            # one, so a stale in-segment cursor offset can never point
            # beyond the new segment's records
            seq = int(cursor.get("seq", -1)) + 1 if cursor else 0
            self._open_segment(seq)
            self._drain_idx = int(cursor.get("idx", 0)) if cursor else 0
            self._drain_seq, self._drain_off = seq, 0
            self._undrained = 0
            return
        # re-attach the append handle to the surviving tail segment
        # (unbuffered, like _open_segment — the write path never flushes)
        self._write_fh = open(self._segments[-1].path, "ab", buffering=0)
        if cursor:
            self._drain_idx = int(cursor.get("idx", 0))
            seq = int(cursor.get("seq", 0))
            off = int(cursor.get("off", 0))
            known = {s.seq: s for s in self._segments}
            if seq in known:
                # a torn tail can shrink the cursored segment underneath a
                # cursor persisted before the crash — clamp, re-push
                self._drain_seq = seq
                self._drain_off = min(off, known[seq].size)
            else:
                # cursored segment already collected (or never synced):
                # restart at the oldest surviving record; replay is
                # idempotent so over-pushing is safe, holes are not
                self._drain_seq = self._segments[0].seq
                self._drain_off = 0
        else:
            self._drain_seq = self._segments[0].seq
            self._drain_off = 0
        self._undrained = self._count_from(self._drain_seq, self._drain_off)
        self._gc_locked()

    @staticmethod
    def _scan_segment(path: Path) -> tuple[int, int]:
        """Return (valid byte length, record count) of the longest valid
        record prefix of ``path``."""
        valid = 0
        records = 0
        with open(path, "rb") as fh:
            while True:
                header = fh.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, crc = _HEADER.unpack(header)
                payload = fh.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                valid += _HEADER.size + length
                records += 1
        return valid, records

    def _count_from(self, seq: int, off: int) -> int:
        """Records at/after (seq, off) — the restart lag. Counted by
        re-reading the partial segment once at open; later bookkeeping is
        incremental."""
        n = 0
        for seg in self._segments:
            if seg.seq < seq:
                continue
            if seg.seq > seq or off == 0:
                n += seg.records
                continue
            with open(seg.path, "rb") as fh:
                fh.seek(off)
                while True:
                    header = fh.read(_HEADER.size)
                    if len(header) < _HEADER.size:
                        break
                    length, _ = _HEADER.unpack(header)
                    fh.seek(length, os.SEEK_CUR)
                    n += 1
        return n

    # -- cursor ------------------------------------------------------------
    def _cursor_path(self) -> Path:
        return self.dir / _CURSOR_FILE

    def _load_cursor(self) -> dict | None:
        try:
            return json.loads(self._cursor_path().read_text())
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, ValueError, OSError) as e:
            # a torn cursor write lost the file content: restart from the
            # oldest record (idempotent replay), never fail open
            log.warning("journal: unreadable cursor (%s); replaying from "
                        "the oldest record", e)
            return None

    def _persist_cursor_locked(self) -> None:
        tmp = self._cursor_path().with_suffix(".tmp")
        payload = json.dumps({"seq": self._drain_seq, "off": self._drain_off,
                              "idx": self._drain_idx})
        with open(tmp, "w") as fh:
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._cursor_path())

    # -- write path --------------------------------------------------------
    def _open_segment(self, seq: int) -> None:
        if self._write_fh is not None:
            self._write_fh.close()
        seg = _Segment(seq, self.dir / _segment_name(seq))
        # unbuffered: every append is flushed to the OS anyway (the drainer
        # reads through a separate handle), so buffering would only add a
        # memcpy plus an extra flush syscall — and under concurrent
        # partition writers, an extra GIL round-trip — per record
        self._write_fh = open(seg.path, "ab", buffering=0)
        seg.size = self._write_fh.tell()
        self._segments.append(seg)

    def _check_closed(self) -> None:
        if self._closed:
            raise RuntimeError("EventJournal is closed")

    def append(self, payload: bytes) -> int:
        """Durably frame one record; returns its global index. Raises
        ``JournalFull`` past ``max_bytes`` of un-collected data (the
        record is NOT written). With policy ``always`` the record is
        fsynced before return; with ``batch`` the caller must ``sync()``
        before acking."""
        t0 = time.perf_counter()
        try:
            return self._append_timed(payload)
        finally:
            _M_APPEND.record(time.perf_counter() - t0)

    def _append_timed(self, payload: bytes) -> int:
        FAULTS.fire("journal.append")
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            self._check_closed()
            if self.size_bytes() + len(frame) > self.max_bytes:
                raise JournalFull(
                    f"journal at capacity ({self.size_bytes()} of "
                    f"{self.max_bytes} bytes undrained)")
            tail = self._segments[-1]
            if tail.size >= self.segment_max_bytes:
                self._sync_locked()  # a rotated-away segment is immutable
                self._open_segment(tail.seq + 1)
                self.rotations += 1
                tail = self._segments[-1]
            # the handle is unbuffered: this lands in the OS (visible to
            # the drainer's read handle) in one syscall; fsync
            # (durability) is the policy's business
            self._write_fh.write(frame)
            tail.size += len(frame)
            tail.records += 1
            self.appended += 1
            self._undrained += 1
            self.unsynced_bytes += len(frame)
            idx = self._drain_idx + self._undrained - 1
            if self.fsync_policy == "always":
                self._sync_locked()
            return idx

    def sync(self) -> None:
        """fsync the active segment (no-op under policy ``never`` — the
        operator chose page-cache durability)."""
        with self._lock:
            self._check_closed()
            if self.fsync_policy != "never":
                self._sync_locked()

    def _sync_locked(self) -> None:
        if self.unsynced_bytes == 0 or self._write_fh is None:
            return
        t0 = time.perf_counter()
        FAULTS.fire("journal.fsync")
        # fdatasync: an append-only segment needs its data and size durable,
        # not atime/mtime — skipping the inode-time flush is the classic WAL
        # sync (PostgreSQL's wal_sync_method default) and measurably cheaper.
        os.fdatasync(self._write_fh.fileno())
        self.synced += 1
        self.unsynced_bytes = 0
        _M_FSYNC.record(time.perf_counter() - t0)

    # -- drain path --------------------------------------------------------
    def peek_batch(self, max_records: int) -> tuple[list[bytes], tuple[int, int, int]]:
        """Up to ``max_records`` undrained payloads in append order, plus
        the cursor position ``(seq, off, idx)`` to ``advance`` to once
        they are safely in the backend. Does not move the cursor."""
        out: list[bytes] = []
        with self._lock:
            self._check_closed()
            seq, off = self._drain_seq, self._drain_off
            by_seq = {s.seq: s for s in self._segments}
            while len(out) < max_records:
                seg = by_seq.get(seq)
                if seg is None or off >= seg.size:
                    nxt = min((s.seq for s in self._segments if s.seq > seq),
                              default=None)
                    if nxt is None:
                        break
                    seq, off = nxt, 0
                    continue
                with open(seg.path, "rb") as fh:
                    fh.seek(off)
                    while len(out) < max_records and off < seg.size:
                        header = fh.read(_HEADER.size)
                        length, _ = _HEADER.unpack(header)
                        out.append(fh.read(length))
                        off += _HEADER.size + length
            return out, (seq, off, self._drain_idx + len(out))

    def advance(self, pos: tuple[int, int, int]) -> None:
        """Persist the drain cursor at ``pos`` and GC segments wholly
        behind it. Called only after the backend accepted the batch."""
        seq, off, idx = pos
        with self._lock:
            self._check_closed()
            self.drained += idx - self._drain_idx
            self._undrained -= idx - self._drain_idx
            self._drain_seq, self._drain_off, self._drain_idx = seq, off, idx
            self._persist_cursor_locked()
            self._gc_locked()

    def _gc_locked(self) -> None:
        keep: list[_Segment] = []
        for seg in self._segments:
            # the active (last) segment is never deleted — the writer
            # holds it open and new appends land there
            if seg.seq < self._drain_seq and seg is not self._segments[-1]:
                try:
                    seg.path.unlink()
                except OSError:
                    keep.append(seg)
                    continue
                self.segments_removed += 1
            else:
                keep.append(seg)
        self._segments = keep

    # -- introspection -----------------------------------------------------
    def size_bytes(self) -> int:
        """On-disk bytes across live segments (the backpressure gauge)."""
        return sum(s.size for s in self._segments)

    @property
    def lag(self) -> int:
        """Undrained record count — 0 means every acked event is in the
        backend."""
        with self._lock:
            return self._undrained

    def stats(self) -> dict:
        with self._lock:
            return {
                "lag": self._undrained,
                "sizeBytes": sum(s.size for s in self._segments),
                "maxBytes": self.max_bytes,
                "segments": len(self._segments),
                "appended": self.appended,
                "drained": self.drained,
                "drainIndex": self._drain_idx,
                "fsyncPolicy": self.fsync_policy,
                "fsyncs": self.synced,
                "unsyncedBytes": self.unsynced_bytes,
                "truncatedBytes": self.truncated_bytes,
                "rotations": self.rotations,
                "segmentsRemoved": self.segments_removed,
            }

    def close(self) -> None:
        """Final fsync (unless policy ``never``) and handle close.
        Idempotent."""
        with self._lock:
            if self._closed:
                return
            if self.fsync_policy != "never":
                try:
                    self._sync_locked()
                except Exception:  # noqa: BLE001 — closing regardless
                    log.exception("journal: final fsync failed")
            if self._write_fh is not None:
                self._write_fh.close()
                self._write_fh = None
            self._closed = True


class PartitionedJournal:
    """N independent ``EventJournal`` shards keyed by
    ``shard_of(entity_type, entity_id, N)``.

    Each partition is a full journal — own segments, cursor, fsync batch,
    GC, backpressure cap (``max_bytes // N``) — so N drainers can append,
    fsync and advance concurrently without sharing a lock or a file.
    ``partitions == 1`` uses the journal directory itself (the original
    flat layout); ``partitions > 1`` uses ``p<k>/`` subdirectories. The
    layout is stamped in ``partitions.json``; opening an existing
    directory with a different count is refused via
    ``JournalLayoutError`` unless every old partition is drained, in
    which case the old layout's files are removed and all partitions
    start empty.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        partitions: int = 1,
        fsync: str = "batch",
        max_bytes: int = 256 * 1024 * 1024,
        segment_max_bytes: int = 16 * 1024 * 1024,
    ):
        partitions = int(partitions)
        if partitions < 1:
            raise ValueError(f"partitions must be >= 1, got {partitions}")
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy must be one of {FSYNC_POLICIES}, got {fsync!r}")
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.num_partitions = partitions
        self.fsync_policy = fsync
        self.max_bytes = max(1, int(max_bytes))
        prior = self._prior_layout()
        if prior is not None and prior != partitions:
            self._resize_from(prior)
        # the total cap is the operator's disk budget — split it evenly so
        # N partitions together never exceed what one journal was allowed
        per_max = max(1, self.max_bytes // partitions)
        per_seg = max(1, min(int(segment_max_bytes), per_max))
        self._parts = [
            EventJournal(self._partition_dir(k), fsync=fsync,
                         max_bytes=per_max, segment_max_bytes=per_seg)
            for k in range(partitions)
        ]
        self._stamp_layout()
        self._publish_gauges()

    # -- layout ------------------------------------------------------------
    def _partition_dir(self, k: int) -> Path:
        return self.dir if self.num_partitions == 1 else self.dir / f"p{k}"

    def _prior_layout(self) -> int | None:
        """Partition count of whatever already lives in ``dir``: the
        stamped marker if readable, else inferred from the files (p<k>/
        subdirs, or flat pre-partitioning segments -> 1)."""
        return _layout_of(self.dir)

    def _resize_from(self, prior: int) -> None:
        """Refuse unless every old partition is drained, then clear the
        old layout so all new partitions start empty — re-hashing
        undrained records across a different N would reorder entities."""
        undrained: list[int] = []
        for k in range(prior):
            d = self.dir if prior == 1 else self.dir / f"p{k}"
            if not d.is_dir():
                continue
            old = EventJournal(d, fsync="never")
            try:
                if old.lag:
                    undrained.append(k)
            finally:
                old.close()
        if undrained:
            raise JournalLayoutError(
                f"journal at {self.dir} has {prior} partition(s) with "
                f"undrained records in {undrained}; resize to "
                f"{self.num_partitions} requires drained journals — stop "
                f"ingest, wait for lag 0, then restart (docs/operations.md "
                f"'Ingestion at scale')")
        for k in range(prior):
            if prior == 1:
                for p in self.dir.glob(_SEGMENT_GLOB):
                    p.unlink()
                (self.dir / _CURSOR_FILE).unlink(missing_ok=True)
            else:
                shutil.rmtree(self.dir / f"p{k}", ignore_errors=True)

    def _stamp_layout(self) -> None:
        tmp = (self.dir / _PARTITIONS_FILE).with_suffix(".tmp")
        tmp.write_text(json.dumps({"partitions": self.num_partitions}))
        os.replace(tmp, self.dir / _PARTITIONS_FILE)

    # -- routing -----------------------------------------------------------
    def partition_of(self, entity_type: str, entity_id: str) -> int:
        from .partition import shard_of

        return shard_of(entity_type, entity_id, self.num_partitions)

    # -- write path --------------------------------------------------------
    def append(self, payload: bytes, partition: int = 0) -> int:
        """Append one record to ``partition``; returns its index local to
        that partition. Raises ``JournalFull`` when THAT partition is at
        capacity — a hot partition backpressures alone."""
        FAULTS.fire("journal.partition_append")
        # gauges are published from advance()/stats(), not here: the append
        # path is the fsync-parallel hot loop and every microsecond of GIL
        # work in it serializes N otherwise-concurrent partition writers
        return self._parts[partition].append(payload)

    def sync(self, partition: int | None = None) -> None:
        """fsync one partition's active segment, or all of them."""
        if partition is not None:
            self._parts[partition].sync()
            return
        for part in self._parts:
            part.sync()

    # -- drain path --------------------------------------------------------
    def peek_batch(self, partition: int,
                   max_records: int) -> tuple[list[bytes], tuple[int, int, int]]:
        return self._parts[partition].peek_batch(max_records)

    def advance(self, partition: int, pos: tuple[int, int, int]) -> None:
        part = self._parts[partition]
        part.advance(pos)
        _M_PART_LAG.set(part._undrained, partition=str(partition))
        _M_PART_FILL.set(self.fill_of(partition), partition=str(partition))

    # -- introspection -----------------------------------------------------
    @property
    def lag(self) -> int:
        return sum(p.lag for p in self._parts)

    def lag_of(self, partition: int) -> int:
        return self._parts[partition].lag

    def size_bytes(self) -> int:
        return sum(p.size_bytes() for p in self._parts)

    def fill_of(self, partition: int) -> float:
        part = self._parts[partition]
        return min(1.0, part.size_bytes() / part.max_bytes)

    def fill_fraction(self) -> float:
        """Fill of the FULLEST partition — the one about to 503. The max
        (not the mean) is the admission-control signal: a single wedged
        partition must brown out ingest for its keys before it bursts."""
        return max(self.fill_of(k) for k in range(self.num_partitions))

    def _publish_gauges(self) -> None:
        for k, part in enumerate(self._parts):
            _M_PART_LAG.set(part.lag, partition=str(k))
            _M_PART_FILL.set(self.fill_of(k), partition=str(k))

    def stats(self) -> dict:
        """Aggregate stats in the single-journal shape (sums), plus a
        ``perPartition`` breakdown for /stats.json."""
        self._publish_gauges()  # scrapes hit /stats.json first — keep fresh
        per = [p.stats() for p in self._parts]
        agg = {
            "lag": sum(s["lag"] for s in per),
            "sizeBytes": sum(s["sizeBytes"] for s in per),
            "maxBytes": self.max_bytes,
            "segments": sum(s["segments"] for s in per),
            "appended": sum(s["appended"] for s in per),
            "drained": sum(s["drained"] for s in per),
            "drainIndex": sum(s["drainIndex"] for s in per),
            "fsyncPolicy": self.fsync_policy,
            "fsyncs": sum(s["fsyncs"] for s in per),
            "unsyncedBytes": sum(s["unsyncedBytes"] for s in per),
            "truncatedBytes": sum(s["truncatedBytes"] for s in per),
            "rotations": sum(s["rotations"] for s in per),
            "segmentsRemoved": sum(s["segmentsRemoved"] for s in per),
            "partitions": self.num_partitions,
            "perPartition": [
                {"partition": k, "lag": s["lag"],
                 "sizeBytes": s["sizeBytes"], "maxBytes": s["maxBytes"],
                 "fill": round(self.fill_of(k), 4),
                 "appended": s["appended"], "drained": s["drained"],
                 "segments": s["segments"],
                 "truncatedBytes": s["truncatedBytes"]}
                for k, s in enumerate(per)
            ],
        }
        return agg

    def close(self) -> None:
        for part in self._parts:
            part.close()


class JournalFollower:
    """Read-only tail of a (possibly partitioned) journal directory
    behind an INDEPENDENT persisted follow cursor per partition — the
    streaming updater's view of the WAL (ISSUE 10; the Kafka
    consumer-group analog: one log, many cursors).

    Strictly an observer of the drainer's journal: never touches
    ``cursor.json``, never opens a write handle, never truncates or
    GCs. Its own progress persists as ``follow-<name>.json`` beside each
    partition's drain cursor (same ``{"seq", "off", "idx"}`` shape, same
    atomic tmp + ``os.replace`` discipline).

    Races it must absorb:

    - **GC behind the drainer** can collect a segment the follower has
      not finished: when the cursored segment is gone, clamp to the
      oldest surviving one (the writer's own ``_recover`` rule).
      Re-reading is safe — the consumer (fold-in) is a deterministic
      per-user recomputation, so replay is idempotent.
    - **A frame mid-write** (or a torn tail before writer recovery)
      scans as invalid: the follower stops AT it without advancing and
      retries next poll — the writer's next flush or its restart-time
      truncation resolves it.
    """

    def __init__(self, directory: str | os.PathLike, *,
                 name: str = "stream", partitions: int | None = None):
        self.dir = Path(directory)
        self.name = name
        if partitions is not None:
            n = int(partitions)
            if n < 1:
                raise ValueError(f"partitions must be >= 1, got {n}")
        else:
            n = _layout_of(self.dir) or 1
        self.num_partitions = n
        self._pos: dict[int, tuple[int, int, int]] = {
            k: self._load_follow(k) for k in range(n)}

    # -- layout / cursor ---------------------------------------------------
    def _partition_dir(self, k: int) -> Path:
        return self.dir if self.num_partitions == 1 else self.dir / f"p{k}"

    def _cursor_path(self, k: int) -> Path:
        return self._partition_dir(k) / f"follow-{self.name}.json"

    def _load_follow(self, k: int) -> tuple[int, int, int]:
        try:
            c = json.loads(self._cursor_path(k).read_text())
            return int(c["seq"]), int(c["off"]), int(c["idx"])
        except FileNotFoundError:
            return (0, 0, 0)  # oldest surviving record (clamped in poll)
        except (json.JSONDecodeError, ValueError, KeyError, TypeError,
                OSError) as e:
            log.warning("journal: unreadable follow cursor %s (%s); "
                        "replaying from the oldest record",
                        self._cursor_path(k).name, e)
            return (0, 0, 0)

    def position(self, partition: int) -> tuple[int, int, int]:
        return self._pos[partition]

    def commit(self, partition: int, pos: tuple[int, int, int]) -> None:
        """Persist the follow cursor — call only once the batch's effect
        is settled downstream (published, or deliberately skipped); a
        transient failure must NOT commit, so a restart replays."""
        self._pos[partition] = (int(pos[0]), int(pos[1]), int(pos[2]))
        path = self._cursor_path(partition)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        with open(tmp, "w") as fh:
            fh.write(json.dumps({"seq": pos[0], "off": pos[1],
                                 "idx": pos[2]}))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    # -- read path ---------------------------------------------------------
    def _segments_on_disk(self, partition: int) -> dict[int, Path]:
        d = self._partition_dir(partition)
        return {_segment_seq(p): p for p in d.glob(_SEGMENT_GLOB)}

    def poll(self, partition: int, max_records: int = 256,
             ) -> tuple[list[bytes], tuple[int, int, int]]:
        """Up to ``max_records`` payloads at/after the follow cursor, in
        append order, plus the position to ``commit`` once they are
        processed. Does not move the cursor."""
        seq, off, idx = self._pos[partition]
        known = self._segments_on_disk(partition)
        out: list[bytes] = []
        if not known:
            return out, (seq, off, idx)
        if seq not in known:
            # cursored segment collected (or cursor from another life):
            # clamp to the oldest surviving record — the _recover rule
            seq, off = min(known), 0
        while len(out) < max_records:
            path = known.get(seq)
            exhausted = path is None  # GC'd under us mid-poll: skip ahead
            if path is not None:
                hit_invalid = False
                try:
                    size = path.stat().st_size
                    with open(path, "rb") as fh:
                        fh.seek(off)
                        while len(out) < max_records:
                            header = fh.read(_HEADER.size)
                            if len(header) < _HEADER.size:
                                break
                            length, crc = _HEADER.unpack(header)
                            payload = fh.read(length)
                            if len(payload) < length \
                                    or zlib.crc32(payload) != crc:
                                hit_invalid = True
                                break
                            out.append(payload)
                            off += _HEADER.size + length
                except OSError:
                    exhausted = True
                if not exhausted:
                    if hit_invalid or len(out) >= max_records:
                        break  # hold position; retry next poll
                    if off < size:
                        break  # partial frame at the active tail: wait
                    exhausted = True  # consumed to its valid end
            if exhausted:
                nxt = min((s for s in known if s > seq), default=None)
                if nxt is None:
                    break
                seq, off = nxt, 0
        return out, (seq, off, idx + len(out))

    def lag(self, partition: int) -> int:
        """Records on disk at/after the follow cursor — the per-partition
        tail-lag gauge (``pio_stream_tail_lag``)."""
        seq, off, _ = self._pos[partition]
        known = self._segments_on_disk(partition)
        if known and seq not in known:
            seq, off = min(known), 0
        n = 0
        for s in sorted(known):
            if s < seq:
                continue
            path = known[s]
            try:
                size = path.stat().st_size
                with open(path, "rb") as fh:
                    pos = off if s == seq else 0
                    fh.seek(pos)
                    while True:
                        header = fh.read(_HEADER.size)
                        if len(header) < _HEADER.size:
                            break
                        length, _crc = _HEADER.unpack(header)
                        pos += _HEADER.size + length
                        if pos > size:
                            break
                        fh.seek(length, os.SEEK_CUR)
                        n += 1
            except OSError:
                continue
        return n
