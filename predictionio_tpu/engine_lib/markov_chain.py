"""Markov chain with top-N-sparse transition rows.

Analog of reference ``MarkovChain`` (e2/src/main/scala/io/prediction/e2/
engine/MarkovChain.scala:201-260): from a sparse transition-count matrix,
keep each row's top-N outgoing transitions normalized by the row sum;
``predict(state)`` returns those (next_state, prob) pairs. The count
matrix is built with one np.add.at scatter instead of the reference's
CoordinateMatrix -> RowMatrix pipeline.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["MarkovChainModel", "train_markov_chain"]


@dataclasses.dataclass
class MarkovChainModel:
    """transition_cols[i]/transition_probs[i]: top-N targets of state i."""

    n_states: int
    top_n: int
    transition_cols: list  # list[np.ndarray[int]]
    transition_probs: list  # list[np.ndarray[float]]

    def predict(self, state: int) -> list[tuple[int, float]]:
        if not (0 <= state < self.n_states):
            raise IndexError(f"state {state} out of range 0..{self.n_states - 1}")
        return list(
            zip(self.transition_cols[state].tolist(),
                self.transition_probs[state].tolist())
        )


def train_markov_chain(
    from_states: np.ndarray,
    to_states: np.ndarray,
    counts: np.ndarray,
    n_states: int,
    top_n: int,
) -> MarkovChainModel:
    """COO transition counts -> row-normalized top-N model
    (MarkovChain.scala:208-245 sparsifies each row to topN by probability)."""
    dense = np.zeros((n_states, n_states), np.float64)
    np.add.at(dense, (from_states, to_states), counts)
    row_sums = dense.sum(axis=1)
    cols, probs = [], []
    for i in range(n_states):
        row = dense[i]
        nz = np.nonzero(row)[0]
        if len(nz) == 0 or row_sums[i] == 0:
            cols.append(np.zeros(0, np.int64))
            probs.append(np.zeros(0, np.float64))
            continue
        order = nz[np.argsort(-row[nz], kind="stable")][:top_n]
        cols.append(order)
        probs.append(row[order] / row_sums[i])
    return MarkovChainModel(
        n_states=n_states, top_n=top_n,
        transition_cols=cols, transition_probs=probs,
    )
