"""Engine-building library — the ``e2`` module analog (reference:
e2/src/main/scala/io/prediction/e2/): reusable algorithms and evaluation
helpers with no framework dependencies."""

from .categorical_nb import CategoricalNaiveBayesModel, train_categorical_nb
from .cross_validation import split_data
from .markov_chain import MarkovChainModel, train_markov_chain

__all__ = [
    "CategoricalNaiveBayesModel", "MarkovChainModel", "split_data",
    "train_categorical_nb", "train_markov_chain",
]
