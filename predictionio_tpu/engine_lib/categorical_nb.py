"""Naive Bayes over string-valued categorical features.

Analog of reference ``CategoricalNaiveBayes`` (e2/src/main/scala/io/
prediction/e2/engine/CategoricalNaiveBayes.scala:23-176): labeled points
whose features are category strings per position; the model scores a point
per label as log prior + sum of per-position conditional log likelihoods,
with a pluggable default for feature values unseen at training
(logScore(point, defaultLikelihood), :103-140).
"""

from __future__ import annotations

import dataclasses
import math
from collections import Counter, defaultdict
from typing import Callable, Sequence

__all__ = ["CategoricalNaiveBayesModel", "train_categorical_nb", "LabeledPoint"]


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """(reference e2/.../engine/LabeledPoint.scala)"""

    label: str
    features: tuple

    def __str__(self):
        return f"({self.label}, {self.features})"


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    """priors: label -> log P(label); likelihoods:
    label -> [per-position {value -> log P(value|label)}]."""

    priors: dict
    likelihoods: dict

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Sequence[float]], float] | None = None,
    ) -> float | None:
        """Score ``point.features`` under ``point.label``
        (CategoricalNaiveBayes.scala:103-140). Unseen feature values use
        ``default_likelihood`` (given the position's known log likelihoods);
        without one, returns None."""
        label = point.label
        if label not in self.priors:
            return None
        ll = self.likelihoods[label]
        if len(point.features) != len(ll):
            raise ValueError(
                f"point has {len(point.features)} features, model expects {len(ll)}"
            )
        total = self.priors[label]
        for pos, value in enumerate(point.features):
            table = ll[pos]
            if value in table:
                total += table[value]
            elif default_likelihood is not None:
                total += default_likelihood(list(table.values()))
            else:
                return None
        return total

    def predict(self, features: Sequence[str]) -> str:
        """Argmax label, scoring unseen values with the position's min
        likelihood (CategoricalNaiveBayes.predict, :143-166)."""
        best, best_score = None, -math.inf
        for label in self.priors:
            s = self.log_score(
                LabeledPoint(label, tuple(features)),
                default_likelihood=lambda lls: min(lls) if lls else -math.inf,
            )
            if s is not None and s > best_score:
                best, best_score = label, s
        return best


def train_categorical_nb(points: Sequence[LabeledPoint]) -> CategoricalNaiveBayesModel:
    """(CategoricalNaiveBayes.train, :29-100)"""
    if not points:
        raise ValueError("no labeled points")
    n_features = len(points[0].features)
    label_counts: Counter = Counter()
    value_counts: dict = defaultdict(lambda: [Counter() for _ in range(n_features)])
    for p in points:
        if len(p.features) != n_features:
            raise ValueError("inconsistent feature arity")
        label_counts[p.label] += 1
        for pos, v in enumerate(p.features):
            value_counts[p.label][pos][v] += 1
    total = sum(label_counts.values())
    priors = {lb: math.log(c / total) for lb, c in label_counts.items()}
    likelihoods = {
        lb: [
            {v: math.log(c / label_counts[lb]) for v, c in value_counts[lb][pos].items()}
            for pos in range(n_features)
        ]
        for lb in label_counts
    }
    return CategoricalNaiveBayesModel(priors=priors, likelihoods=likelihoods)
