"""k-fold data splitting for evaluation.

Analog of reference ``CrossValidation`` (e2/src/main/scala/io/prediction/
e2/evaluation/CrossValidation.scala:285-320): element i goes to test fold
``i % k``; yields (training subset, eval info, test subset) per fold —
the same deterministic modulo split the reference uses so results are
reproducible without shuffling.
"""

from __future__ import annotations

from typing import Callable, Sequence, TypeVar

T = TypeVar("T")
Q = TypeVar("Q")
A = TypeVar("A")

__all__ = ["split_data"]


def split_data(
    eval_k: int,
    data: Sequence[T],
    to_query_actual: Callable[[T], tuple[Q, A]],
) -> list[tuple[list[T], dict, list[tuple[Q, A]]]]:
    if eval_k < 2:
        raise ValueError("eval_k must be >= 2")
    folds = []
    for fold in range(eval_k):
        train = [x for i, x in enumerate(data) if i % eval_k != fold]
        test = [to_query_actual(x) for i, x in enumerate(data) if i % eval_k == fold]
        folds.append((train, {"fold": fold}, test))
    return folds
